"""Continuous-batching decode engine: fixed shapes, zero recompiles.

The engine runs one of two KV layouts behind the same slot API:

* ``page_size=0`` — the PR-4 monolithic layout: per-slot worst-case rows
  in a :class:`~distributed_tensorflow_tpu.serve.kv_pool.SlotKVPool`.
  Kept verbatim as the parity baseline.

* ``page_size>0`` (default) — the paged layout: one physical page pool
  (:class:`~distributed_tensorflow_tpu.serve.kv_pool.PagedKVPool`) plus
  per-slot page tables. Every jitted program gathers a slot's logical
  ``(kv, max_len, dh)`` cache from its table row, runs the SAME model
  code as the monolithic path, and scatters touched pages back. The
  table is a host numpy array passed as a TRACED operand of fixed shape
  ``(slots, pages_per_slot)``, so rebinding pages never retraces; unbound
  entries point at the reserved trash page, which absorbs the fixed-shape
  scatters of masked lanes.

Jitted programs (all compiled at :meth:`SlotEngine.warmup`, after which
the compile count must never grow — the ``RecompileSentinel`` contract):

* **prefill** — one batched causal forward of a PADDED ``(1, width)``
  prompt where ``width`` is the narrowest compiled bucket (a fixed set,
  ``prefill_buckets``, largest always ``prefill_len``) holding the real
  tokens, then the request's FIRST token sampled at its true last prompt
  position. Under paging the forward starts at cache ``len = m0`` where
  ``m0`` tokens of KV were ADOPTED from the prefix cache (copy-free page
  sharing) — only the prompt TAIL is computed, through a tail-sized
  bucket, which is what collapses TTFT for shared-system-prompt traffic.

* **decode step** — ``steps_per_sync`` micro-steps over the whole slot
  batch fused into one ``lax.scan``; per-slot traced lengths, per-slot
  sampling (``sample_logits_batched``), inactive lanes masked. The paged
  variant scatters back only the ONE page each slot wrote (its private
  boundary page — never a shared prefix page, since writes land at
  positions ``>= p``).

* **speculative verify** (``spec_k > 0``, two compiled variants) — the
  host drafts ``spec_k`` tokens by prompt-lookup (n-gram continuation of
  the slot's own history; ``models/decoding.propose_ngram_drafts``) and
  ONE forward of ``[cur_tok, d_0..d_{k-1}]`` verifies them. All-greedy
  rounds run the greedy variant: the emitted stream is ``targets[:a+1]``
  where ``targets`` are the argmax outputs and ``a`` counts leading
  ``d_i == targets[i]`` matches — each accepted draft equals the token
  greedy decoding would have fed, so by induction the output is
  TOKEN-IDENTICAL to the plain path. Rounds with any sampled lane run
  the rejection-sampling variant (``models/decoding.
  rejection_verify_row``): draft ``i`` is accepted with probability
  ``min(1, p/q)`` against the target's FILTERED distribution (same
  ``filter_logits_batched`` as the plain sampled step) and the first
  rejection resamples from the normalized residual — each emitted token
  is an exact draw from the plain sampled-decode distribution, so
  speculation changes latency, never content (greedy) or the output
  DISTRIBUTION (sampled). Rejected drafts leave stale KV above the
  accepted length, which the overwrite invariant below already makes
  unreadable.

* **tree verify** (``spec_branches > 1``, replaces the linear verify
  programs) — each slot contributes a ``(spec_branches, spec_k)`` draft
  TREE (branch 0 the linear drafter's block; extra branches are
  alternative n-gram continuations pooled across ALL active slots'
  histories — the batch-wide shared draft pool) and ONE widened forward
  of ``1 + B*k`` tokens verifies every branch under a static
  tree-attention ancestor mask. Greedy lanes accept the best branch's
  longest matching path token-identically (ties to branch 0, so
  accepted-per-verify dominates the linear baseline); sampled lanes run
  sequential multi-candidate rejection sampling over the branch roots
  then the linear verify along the winner
  (``models/decoding.tree_rejection_verify_row`` — still lossless). The
  accepted branch's KV block is compacted onto the slot's canonical
  timeline inside the program before the page scatter.

* **chunked prefill** (``prefill_chunk_tokens > 0``, paged only) — a
  prompt whose post-adoption tail exceeds the chunk width is fed across
  ENGINE ITERATIONS instead of one monolithic forward: full-width
  intermediate chunks through the SAME compiled bucket programs (their
  sampled token is discarded), then one suffix-aligned final chunk whose
  fed window ends exactly at position ``p-1`` so the first token is
  sampled at the true last prompt position. The slot sits in a
  ``PREFILLING`` phase meanwhile (``start`` returns ``(None, False)``)
  and co-resident decode slots keep stepping every iteration —
  Sarathi-style stall-free batching. Because a chunk at offset ``m``
  writes positions ``[m, m+w)`` BEFORE any later chunk attends them
  (write-before-attend, below), resuming at ``len = m`` across separate
  program invocations is exactly as correct as the one-shot tail
  forward. No new programs: chunk calls reuse the bucket set, so the
  zero-recompile contract is untouched.

Drafting (``spec_k > 0``) comes in two flavors behind the same verify:
the zero-weight n-gram prompt-lookup drafter (default), or a LEARNED
draft model (``draft_params``/``draft_cfg``: a truncated-layer head
distilled from the target by ``tools/train_draft.py``) that greedily
rolls ``spec_k`` tokens from a ``draft_window``-token suffix of the
slot's history in one jitted program. Draft quality only moves the
accept rate — the verify forward makes greedy output token-identical
either way.

Correctness invariant for slot reuse (why freed slots are not zeroed, pad
junk is harmless, and rejected-draft KV needs no rollback): after prefill
the filled length is the TRUE prompt length ``p``, and a decode step at
length ``len`` writes position ``len`` BEFORE attending keys ``0..len``
(the cache append precedes the score einsum in ``attention_sublayer``).
By induction every attended key was written by this request — stale rows
sit strictly above the filled length until the step that overwrites them.
``tests/test_serve_engine.py::test_slot_reuse_isolation`` pins this; the
paged/spec parity matrix lives in ``tests/test_paged_kv.py``, the
chunked-prefill parity matrix in ``tests/test_serve_chunked.py``.

Host/device split: the big pool buffers live on device and are DONATED
through every program (in-place turnover); the per-slot registers
(lengths, current token, sampling params, budgets, token history for the
drafter) are small host numpy arrays passed in each call — the host is
the scheduler's view, the device never holds control state the host also
needs.
"""

from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.models.decoding import (
    build_draft_fn,
    decode_step,
    filter_logits_batched,
    init_cache,
    propose_ngram_drafts,
    propose_ngram_tree,
    rejection_verify_row,
    sample_logits_batched,
    tree_rejection_verify_row,
)
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.serve.kv_pool import (
    TRASH_PAGE,
    InsufficientPages,
    PagedKVPool,
    PrefixCache,
    SlotKVPool,
)

__all__ = ["SlotEngine", "ShardedSlotEngine"]


class SlotEngine:
    """Fixed-capacity continuous-batching engine over one model replica.

    Drive it with :class:`~distributed_tensorflow_tpu.serve.scheduler.
    Scheduler` (request queue + admission control) or directly:
    ``acquire_slot`` → ``start`` (prefill, returns the first token) →
    repeated ``step`` (one batch round; token count varies — plain rounds
    yield ``steps_per_sync`` rows, speculative rounds up to ``spec_k+1``)
    → ``release``. Single-threaded by contract: one thread owns the
    engine. ``start`` raises :class:`InsufficientPages` when the paged
    pool cannot back the request right now — release the slot and retry
    once in-flight requests free pages.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 4,
        max_len: int | None = None,
        prefill_len: int | None = None,
        steps_per_sync: int = 1,
        sentinel=None,
        page_size: int | None = None,
        kv_pages: int = 0,
        prefix_cache: bool = True,
        spec_k: int = 0,
        spec_branches: int = 1,
        prefill_buckets: tuple = (),
        prefill_chunk_tokens: int = 0,
        draft_params=None,
        draft_cfg=None,
        draft_window: int = 16,
    ):
        max_len = int(max_len or cfg.max_seq_len)
        prefill_len = int(prefill_len or max(1, max_len // 2))
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"max_len {max_len} > model max_seq_len {cfg.max_seq_len}"
            )
        if not 1 <= prefill_len <= max_len:
            raise ValueError(
                f"prefill_len {prefill_len} outside [1, max_len {max_len}]"
            )
        if steps_per_sync < 1:
            raise ValueError(f"steps_per_sync must be >= 1, got {steps_per_sync}")
        if page_size is None:
            # Default to paging; degrade to one whole-row page per slot
            # when 16 doesn't divide max_len rather than erroring.
            page_size = 16 if max_len % 16 == 0 else max_len
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if spec_k and not page_size:
            raise ValueError("spec_k > 0 requires the paged KV layout")
        spec_branches = int(spec_branches)
        if spec_branches < 1:
            raise ValueError(
                f"spec_branches must be >= 1, got {spec_branches}"
            )
        if spec_branches > 1:
            if not spec_k:
                raise ValueError("spec_branches > 1 requires spec_k > 0")
            if getattr(cfg, "attention_window", None) is not None:
                # Tree verify feeds a non-chain block: in-block positions
                # are non-monotone in cache-write order, which the sliding
                # window's relative-offset mask cannot express.
                raise ValueError(
                    "spec_branches > 1 (tree speculation) is incompatible "
                    "with attention_window"
                )
            if 1 + spec_branches * spec_k > max_len - 1:
                raise ValueError(
                    f"tree verify width 1 + {spec_branches}*{spec_k} "
                    f"exceeds max_len - 1 ({max_len - 1}); shrink "
                    "spec_branches/spec_k"
                )
        self.cfg = cfg
        # Place params through the same path swap candidates stage through
        # (``_place_params``): a checkpoint bundle arrives as host numpy,
        # and numpy vs device-array arguments key DIFFERENT pjit cache
        # entries — boot params must look exactly like adopted ones or the
        # first post-swap round grows the compile caches (the poll-mode
        # sentinel counts that as a recompile) and re-uploads weights every
        # dispatch until then.
        self.params = self._place_params(params)
        self.model = TransformerLM(cfg)
        self.slots = int(slots)
        self.max_len = max_len
        self.prefill_len = prefill_len
        self.steps_per_sync = int(steps_per_sync)
        self.page_size = int(page_size)
        self.paged = self.page_size > 0
        self.spec_k = int(spec_k)
        self.spec_branches = spec_branches
        # Positions a verify round writes above each slot's length: the
        # whole fed block. _decode_round's end-of-window fallback guard
        # uses this (tree blocks are wider than linear ones).
        self._spec_write = (
            1 + spec_branches * self.spec_k
            if spec_branches > 1
            else self.spec_k + 1
        )
        # Prefill width buckets (paged only): the prefill program is
        # shape-polymorphic in its tokens width, so a FIXED set of widths
        # is just a fixed set of compiled programs — warmup compiles every
        # member and the zero-recompile invariant is untouched. A request
        # whose post-adoption tail fits a narrow bucket prefills through
        # it instead of paying the full prefill_len-wide forward; this is
        # what turns prefix-cache hits into TTFT wins (without buckets the
        # padded tail costs the same compute as a cold prompt). The
        # largest bucket is always prefill_len — the cold-prompt path.
        buckets = {int(b) for b in prefill_buckets} if self.paged else set()
        for b in buckets:
            if not 1 <= b <= prefill_len:
                raise ValueError(
                    f"prefill bucket {b} outside [1, prefill_len "
                    f"{prefill_len}]"
                )
        buckets.add(prefill_len)
        # Chunked prefill (paged only): 0 = auto (chunk width =
        # prefill_len, i.e. prompts up to prefill_len keep the one-shot
        # path byte-for-byte and only LONGER prompts chunk), -1 = off
        # (prefill_len stays a hard prompt cap, the pre-chunking
        # contract). Widths above the chunk are pruned from the bucket
        # set — the one-shot path never sees a tail wider than the chunk
        # once chunking is on, so they would be dead compiled programs.
        c = int(prefill_chunk_tokens)
        if self.paged and c >= 0:
            if c == 0:
                c = prefill_len
            if not 1 <= c <= prefill_len:
                raise ValueError(
                    f"prefill_chunk_tokens {c} outside [1, prefill_len "
                    f"{prefill_len}]"
                )
            buckets = {b for b in buckets if b <= c}
            buckets.add(c)
        else:
            c = -1
        self.prefill_chunk_tokens = c
        self.prefill_buckets = tuple(sorted(buckets))
        # Learned drafter (optional): a small draft LM rolled greedily for
        # spec_k tokens from a draft_window-token suffix of each slot's
        # history — one jitted program, compiled at warmup alongside the
        # verify. Replaces the host n-gram drafter when provided; the
        # verify loop (and therefore token-identical greedy output) is
        # unchanged either way.
        if draft_params is not None:
            if not self.spec_k:
                raise ValueError("draft_params requires spec_k > 0")
            if draft_cfg is None:
                raise ValueError("draft_params requires draft_cfg")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}"
                )
            # The draft cache holds window + spec_k positions; clamp the
            # window so it fits the draft model's trained length.
            draft_window = min(
                int(draft_window), draft_cfg.max_seq_len - self.spec_k
            )
            if draft_window < 1:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} too short "
                    f"for spec_k {self.spec_k}"
                )
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.draft_window = int(draft_window)
        self.drafter = "model" if draft_params is not None else "ngram"
        # Optional obs.perf.RecompileSentinel: fed the compile-cache size
        # after warmup and every round, it turns the zero-recompile
        # invariant into the alerting ``recompile_events_total`` metric.
        self.sentinel = sentinel
        # Deploy surface (serve/deploy/): the checkpoint step currently
        # serving and the named variant it belongs to. adopt_weights()
        # maintains both; /healthz and the fleet registry report them.
        self.weight_version = 0
        self.serving_variant = ""
        # Mesh topology: the base engine is one fully-replicated process.
        # ShardedSlotEngine sets these BEFORE delegating here so the pool
        # and program hooks below see them.
        if not hasattr(self, "tp"):
            self.tp = 1
            self.mesh = None
        if self.paged:
            self.pool = self._build_pool(cfg, max_len, kv_pages)
            self.prefix = PrefixCache(self.pool) if prefix_cache else None
        else:
            self.pool = SlotKVPool(cfg, self.slots, max_len)
            self.prefix = None

        # Per-slot host registers. Fixed dtypes — the jit signatures (and
        # therefore the zero-recompile guarantee) depend on them.
        n = self.slots
        self.active = np.zeros(n, bool)
        self.lengths = np.zeros(n, np.int32)  # filled cache prefix per slot
        self.cur_tok = np.zeros(n, np.int32)  # last sampled, next to feed
        self.temp = np.zeros(n, np.float32)
        self.top_k = np.zeros(n, np.int32)
        self.top_p = np.zeros(n, np.float32)
        self.seed = np.zeros(n, np.uint32)
        self.made = np.zeros(n, np.int32)  # tokens generated so far
        self.budget = np.ones(n, np.int32)  # max_new_tokens per slot
        self.eos = np.full(n, -1, np.int32)  # -1 = no eos stop
        # Prompt + emitted tokens per slot — the drafter's corpus. Bounded
        # by max_len (prompt + budget <= max_len is validated at start).
        self.history = np.zeros((n, max_len), np.int32)
        self.hist_len = np.zeros(n, np.int32)
        # PREFILLING phase state: slots mid-chunked-prefill are neither
        # free nor active. _pf holds each one's chunk plan; _pf_queue is
        # the round-robin order chunks are spent in.
        self.prefilling = np.zeros(n, bool)
        self._pf: dict[int, dict] = {}
        self._pf_queue: deque[int] = deque()
        # Cumulative fast-path counters; the scheduler mirrors these into
        # ServingMetrics (serve_prefix_hit_rate / serve_spec_accept_rate).
        # The aggregate spec keys stay (pre-drafter dashboards); the
        # per-drafter keys feed the drafter-labeled /metrics counters.
        self.stats = {
            "prefix_tokens_matched": 0,
            "prefix_tokens_total": 0,
            "spec_drafts_accepted": 0,
            "spec_drafts_proposed": 0,
            "spec_drafts_accepted_ngram": 0,
            "spec_drafts_proposed_ngram": 0,
            "spec_drafts_accepted_model": 0,
            "spec_drafts_proposed_model": 0,
            "spec_rounds": 0,
            "spec_rounds_sampled": 0,
            "spec_verifies": 0,
            "plain_rounds": 0,
            "prefill_chunks": 0,
            "prefill_tokens_last_iter": 0,
        }
        # Per-slot accepted-draft counts, one sample per (slot, verify
        # round) — loadgen/metrics read accepted-per-verify p50/p99 off
        # this bounded window.
        self.accept_samples: deque[int] = deque(maxlen=4096)
        self._force_plain = False  # warmup hook: compile the non-spec path

        model, k_sync = self.model, self.steps_per_sync
        ps, pps = self.page_size, getattr(self.pool, "pages_per_slot", 0)

        # -- paged layout plumbing ---------------------------------------
        # A slot's logical cache is the gather of its table row; the
        # inverse reshape splits a logical buffer back into pages. Both
        # are layout-generic over the cache leaf kinds (k/v rows
        # (pages, kv, ps, dh) and int8 scales (pages, kv, ps)).

        def gather_row(buf, row):
            g = jnp.swapaxes(buf[row], 0, 1)  # (kv, pps, ps[, dh])
            return g.reshape((g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:])

        def split_pages(x):
            # (kv, max_len[, dh]) -> (pps, kv, ps[, dh])
            x = x.reshape((x.shape[0], pps, ps) + x.shape[2:])
            return jnp.swapaxes(x, 0, 1)

        def gather_cache(pool_layers, row, length):
            return {
                "layers": [
                    {k: gather_row(v, row)[None] for k, v in l.items()}
                    for l in pool_layers
                ],
                "len": length,
            }

        def make_prefill(sampled: bool):
            if not self.paged:

                def prefill_fn(params, tokens, length, temp, top_k, top_p, seed):
                    """(1, prefill_len) padded prompt → (fresh (1, max_len)
                    cache layers, first sampled token). ``length`` is the
                    true prompt length (traced — heterogeneous prompts
                    share the compile)."""
                    cache = init_cache(cfg, 1, max_len)
                    logits, cache = model.apply(
                        {"params": params}, tokens, cache=cache
                    )
                    last = jnp.take(logits[0], length - 1, axis=0)  # (V,)
                    first = _select(sampled, last, temp, top_k, top_p, seed)
                    return cache["layers"], first

                return prefill_fn

            def prefill_fn(
                pool_layers, params, tokens, length, prefix_len, row,
                temp, top_k, top_p, seed,
            ):
                """Tail prefill into the slot's pages. ``prefix_len`` (m0,
                a page multiple, traced) tokens of KV are already present
                via adopted shared pages; the forward runs the padded tail
                at cache ``len = m0`` so positions/rotations line up, and
                the first token is sampled at the true last prompt
                position ``length - 1`` (tail-local index
                ``length - m0 - 1``). The scatter-back writes EVERY page
                in the row: adopted pages round-trip their gathered values
                (byte-identical — the forward never writes below m0) and
                unbound tail entries land in the trash page."""
                cache = gather_cache(pool_layers, row, prefix_len)
                logits, cache = model.apply(
                    {"params": params}, tokens, cache=cache
                )
                last = jnp.take(logits[0], length - prefix_len - 1, axis=0)
                first = _select(sampled, last, temp, top_k, top_p, seed)
                new_pool = [
                    {
                        k: pl[k].at[row].set(split_pages(cl[k][0]))
                        for k in pl
                    }
                    for pl, cl in zip(pool_layers, cache["layers"])
                ]
                return new_pool, first

            return prefill_fn

        def _select(sampled, last, temp, top_k, top_p, seed):
            if sampled:  # dttlint: disable=jit-purity -- static program-variant flag: the factory bakes sampled in as a Python bool (one jitted program per variant)
                key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)
                return sample_logits_batched(
                    last[None], key[None], temp[None], top_k[None], top_p[None]
                )[0]
            return jnp.argmax(last).astype(jnp.int32)

        def make_step(sampled: bool):
            if not self.paged:

                def step_fn(
                    params, layers, active, lengths, tok,
                    temp, top_k, top_p, seed, made, budget, eos,
                ):
                    """One engine round = ``steps_per_sync`` scanned
                    micro-steps. Returns the new pool/registers plus
                    ``(k, slots)`` sampled tokens and their validity mask
                    (a slot's tokens are valid while it was active at
                    sampling time — the final token of a finishing slot is
                    valid, the masked lanes after it are not)."""

                    def one(slot_layers, length, t):
                        cache = {
                            "layers": [
                                {k: v[None] for k, v in l.items()}
                                for l in slot_layers
                            ],
                            "len": length,
                        }
                        cache, logits = decode_step(
                            model, params, cache, t[None, None]
                        )
                        out_layers = [
                            {k: v[0] for k, v in l.items()}
                            for l in cache["layers"]
                        ]
                        return out_layers, logits[0]

                    def micro(carry, _):
                        layers, active, lengths, tok, made = carry
                        layers, logits = jax.vmap(one)(layers, lengths, tok)
                        nxt = _pick(sampled, logits, seed, made,
                                    temp, top_k, top_p)
                        nxt = jnp.where(active, nxt, tok)
                        new_lengths = jnp.where(active, lengths + 1, lengths)
                        new_made = jnp.where(active, made + 1, made)
                        finished = active & (
                            (new_made >= budget) | (nxt == eos)
                        )
                        return (
                            (layers, active & ~finished, new_lengths, nxt,
                             new_made),
                            (nxt, active),
                        )

                    carry, (toks, valid) = jax.lax.scan(
                        micro, (layers, active, lengths, tok, made), None,
                        length=k_sync,
                    )
                    layers, active, lengths, tok, made = carry
                    return layers, active, lengths, tok, made, toks, valid

                return step_fn

            def step_fn(
                pool_layers, params, ptabs, active, lengths, tok,
                temp, top_k, top_p, seed, made, budget, eos,
            ):
                """Paged decode round. Identical control flow to the
                monolithic variant; each micro-step gathers every slot's
                logical cache from its table row, appends one token, and
                scatters back only the single page each slot wrote (page
                ``length // page_size`` — always slot-private: decode
                positions are ``>= p``, strictly above every shared full
                prompt page). Inactive lanes scatter into the trash
                page."""

                def one(row, length, t):
                    cache = gather_cache(pool_layers_ref[0], row, length)
                    cache, logits = decode_step(
                        model, params, cache, t[None, None]
                    )
                    wp = length // ps

                    def grab(x):
                        starts = (0, wp * ps) + (0,) * (x.ndim - 2)
                        sizes = (x.shape[0], ps) + x.shape[2:]
                        return jax.lax.dynamic_slice(x, starts, sizes)

                    written = [
                        {k: grab(v[0]) for k, v in l.items()}
                        for l in cache["layers"]
                    ]
                    return written, logits[0]

                pool_layers_ref = [pool_layers]

                def micro(carry, _):
                    pool_layers, active, lengths, tok, made = carry
                    pool_layers_ref[0] = pool_layers
                    written, logits = jax.vmap(one)(ptabs, lengths, tok)
                    wp = lengths // ps
                    dest = ptabs[jnp.arange(ptabs.shape[0]), wp]
                    dest = jnp.where(active, dest, TRASH_PAGE)
                    pool_layers = [
                        {k: pl[k].at[dest].set(written[li][k]) for k in pl}
                        for li, pl in enumerate(pool_layers)
                    ]
                    nxt = _pick(sampled, logits, seed, made,
                                temp, top_k, top_p)
                    nxt = jnp.where(active, nxt, tok)
                    new_lengths = jnp.where(active, lengths + 1, lengths)
                    new_made = jnp.where(active, made + 1, made)
                    finished = active & ((new_made >= budget) | (nxt == eos))
                    return (
                        (pool_layers, active & ~finished, new_lengths, nxt,
                         new_made),
                        (nxt, active),
                    )

                carry, (toks, valid) = jax.lax.scan(
                    micro, (pool_layers, active, lengths, tok, made), None,
                    length=k_sync,
                )
                pool_layers, active, lengths, tok, made = carry
                return pool_layers, active, lengths, tok, made, toks, valid

            return step_fn

        def _pick(sampled, logits, seed, made, temp, top_k, top_p):
            if sampled:
                keys = jax.vmap(
                    lambda s, m: jax.random.fold_in(jax.random.PRNGKey(s), m)
                )(seed, made)
                return sample_logits_batched(
                    logits, keys, temp, top_k, top_p
                )
            return jnp.argmax(logits, -1).astype(jnp.int32)

        def make_spec(rs: bool):
            S = self.spec_k + 1

            def spec_fn(
                pool_layers, params, ptabs, active, lengths, tok, drafts,
                temp, top_k, top_p, seed, made, budget, eos,
            ):
                """One speculative verify round. Feeds
                ``[cur_tok, d_0..d_{k-1}]`` (S tokens) per slot in ONE
                forward; ``targets = argmax(logits)`` are the greedy
                continuations after each fed token.

                Greedy lanes (and the whole ``rs=False`` variant): with
                ``a`` = leading ``d_i == targets[i]`` matches, the emitted
                stream is ``targets[:a+1]`` — token-identical to ``a+1``
                plain rounds, because each accepted draft IS the token the
                plain path would have fed next.

                Sampled lanes (``rs=True`` variant, rows with
                ``temp > 0``): rejection-sampling verify
                (``models/decoding.rejection_verify_row``) over the SAME
                forward's logits, filtered with the slot's sampling params
                by the SAME ``filter_logits_batched`` the plain path uses
                — each emitted token is an exact draw from the plain
                sampled-decode distribution (lossless speculation), and
                ``a`` counts the accepted drafts.

                Either way the emitted count is ``a + 1`` before budget /
                eos truncation, so the KV bookkeeping is shared: all S
                positions are written (then truncated by moving
                ``lengths`` up only ``n_final``) — rejected rows sit above
                the filled length, stale-until-overwritten, per the module
                invariant. The whole table row scatters back (shared
                prefix pages get byte-identical values; overrun past the
                slot's bound pages lands in trash)."""

                def one(row, length, t, d):
                    cache = gather_cache(pool_layers, row, length)
                    x = jnp.concatenate([t[None], d])[None]  # (1, S)
                    logits, cache = model.apply(
                        {"params": params}, x, cache=cache
                    )
                    pages = [
                        {k: split_pages(v[0]) for k, v in l.items()}
                        for l in cache["layers"]
                    ]
                    return pages, logits[0]

                pages, logits = jax.vmap(one)(ptabs, lengths, tok, drafts)
                targets = jnp.argmax(logits, -1).astype(jnp.int32)
                dest = jnp.where(active[:, None], ptabs, TRASH_PAGE)
                new_pool = [
                    {k: pl[k].at[dest].set(pages[li][k]) for k in pl}
                    for li, pl in enumerate(pool_layers)
                ]
                # Acceptance: longest accepted draft prefix, then budget /
                # eos truncation on the emitted stream E.
                match = drafts == targets[:, : S - 1]  # (slots, S-1)
                lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
                a = lead.sum(axis=1)  # (slots,) accepted drafts
                E = targets  # (slots, S) emitted stream candidates
                if rs:
                    def verify(lg, d, tm, tk, tp_, sd, md):
                        filt = filter_logits_batched(
                            lg,
                            jnp.full((S,), tm),
                            jnp.full((S,), tk, jnp.int32),
                            jnp.full((S,), tp_),
                        )
                        return rejection_verify_row(filt, d, sd, md)

                    E_rs, a_rs = jax.vmap(verify)(
                        logits, drafts, temp, top_k, top_p, seed, made
                    )
                    is_sampled = temp > 0.0
                    a = jnp.where(is_sampled, a_rs, a)
                    E = jnp.where(is_sampled[:, None], E_rs, E)
                n0 = a + 1  # candidate emit count
                n1 = jnp.minimum(n0, budget - made)
                idx = jnp.arange(S)[None, :]
                eos_in = (E == eos[:, None]) & (idx < n1[:, None])
                any_eos = eos_in.any(axis=1)
                first_eos = jnp.argmax(eos_in, axis=1)
                n_final = jnp.where(any_eos, first_eos + 1, n1)
                n_final = jnp.where(active, n_final, 0)
                new_lengths = lengths + n_final
                new_made = made + n_final
                rows = jnp.arange(E.shape[0])
                last = jnp.clip(n_final - 1, 0, S - 1)
                new_tok = jnp.where(active, E[rows, last], tok)
                finished = active & ((new_made >= budget) | any_eos)
                valid = (idx < n_final[:, None]) & active[:, None]
                accepted = jnp.where(active, jnp.minimum(a, n_final - 1), 0)
                return (
                    new_pool, active & ~finished, new_lengths, new_tok,
                    new_made, E.T, valid.T, accepted,
                )

            return spec_fn

        def make_tree_spec(rs: bool):
            B, D = self.spec_branches, self.spec_k
            N = 1 + B * D
            S = D + 1
            # Static tree topology. Node (b, j) — branch b's depth-(j+1)
            # draft — is FED (and cache-written) at flat index 1 + b*D + j,
            # but its SEMANTIC position is length + 1 + j: write order is
            # branch-major while causal order is per-branch. The ancestor
            # mask, depth vector and parent table below encode that once,
            # as compile-time constants.
            anc = np.zeros((N, N), bool)
            anc[0, 0] = True
            par = np.zeros((B, D), np.int32)
            for b in range(B):
                for j in range(D):
                    r = 1 + b * D + j
                    anc[r, 0] = True
                    anc[r, 1 + b * D : r + 1] = True
                    par[b, j] = 0 if j == 0 else 1 + b * D + (j - 1)
            self_mask = jnp.asarray(anc)
            depth = jnp.asarray(
                np.concatenate([[0], 1 + np.tile(np.arange(D), B)]),
                jnp.int32,
            )
            par = jnp.asarray(par)

            def tree_fn(
                pool_layers, params, ptabs, active, lengths, tok, drafts,
                temp, top_k, top_p, seed, made, budget, eos,
            ):
                """One shared-draft TREE verify round. Feeds
                ``[cur_tok, branch_0 d_0..d_{D-1}, ..., branch_{B-1} ...]``
                (N = 1 + B*D tokens) per slot in ONE widened forward under
                the static ancestor ``self_mask`` — every branch verifies
                against the same committed prefix in the same program
                (SpecInfer-style tree attention), with semantic positions
                following tree depth rather than write order.

                Greedy lanes accept, per branch, the longest prefix of
                drafts matching the target's greedy outputs at their PARENT
                rows, then take the best branch (``argmax`` — first-max
                ties resolve to branch 0, the linear drafter's block, so
                accepted-per-verify dominates the linear baseline pointwise
                on the same trajectory and the emitted stream stays
                token-identical to plain greedy decode). Sampled lanes run
                ``tree_rejection_verify_row``: sequential multi-candidate
                rejection sampling over the B roots, then the PR 11 linear
                verify along the accepted branch — lossless per token.

                The accepted branch's KV block is COMPACTED in-program onto
                the canonical slot timeline (rows ``length+1+bsel*D..`` move
                to ``length+1``) before the page scatter; everything at or
                above ``length + 1 + D`` is stale junk the write-before-
                attend invariant keeps unreadable. Outputs match the linear
                verify's layout exactly (emitted streams are (S, slots)
                with S = D + 1), so round bookkeeping is shared."""

                def one(row, length, t, d, tm, tk, tp_, sd, md):
                    cache = gather_cache(pool_layers, row, length)
                    x = jnp.concatenate([t[None], d.reshape(-1)])[None]
                    positions = (length + depth)[None]
                    logits, cache = model.apply(
                        {"params": params}, x, cache=cache,
                        positions=positions, self_mask=self_mask,
                    )
                    lg = logits[0]  # (N, V)
                    targets = jnp.argmax(lg, -1).astype(jnp.int32)
                    # Greedy: per-branch leading-match runs against each
                    # node's PARENT row target, best branch wins.
                    match = d == jnp.take(targets, par)  # (B, D)
                    lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
                    acc_b = lead.sum(axis=1)  # (B,)
                    bsel_g = jnp.argmax(acc_b).astype(jnp.int32)
                    rows_g = jnp.concatenate(
                        [jnp.zeros((1,), jnp.int32),
                         1 + bsel_g * D + jnp.arange(D, dtype=jnp.int32)]
                    )
                    E_g = jnp.take(targets, rows_g)  # (S,)
                    a_g = acc_b[bsel_g]
                    if rs:
                        filt = filter_logits_batched(
                            lg,
                            jnp.full((N,), tm),
                            jnp.full((N,), tk, jnp.int32),
                            jnp.full((N,), tp_),
                        )
                        E_s, a_s, bsel_s = tree_rejection_verify_row(
                            filt, d, sd, md
                        )
                        is_s = tm > 0.0
                        E = jnp.where(is_s, E_s, E_g)
                        a = jnp.where(is_s, a_s, a_g)
                        bsel = jnp.where(is_s, bsel_s, bsel_g)
                    else:
                        E, a, bsel = E_g, a_g, bsel_g

                    def compact(leaf):
                        # leaf (1, kv, S_max[, dh]); move the selected
                        # branch's D rows to the canonical offsets right
                        # after cur_tok's row (bsel = 0 is the identity).
                        starts = (0, 0, length + 1 + bsel * D)
                        starts += (0,) * (leaf.ndim - 3)
                        sizes = (leaf.shape[0], leaf.shape[1], D)
                        sizes += leaf.shape[3:]
                        blk = jax.lax.dynamic_slice(leaf, starts, sizes)
                        dst = (0, 0, length + 1) + (0,) * (leaf.ndim - 3)
                        return jax.lax.dynamic_update_slice(leaf, blk, dst)

                    pages = [
                        {k: split_pages(compact(v)[0]) for k, v in l.items()}
                        for l in cache["layers"]
                    ]
                    return pages, E, a, bsel

                pages, E, a, _bsel = jax.vmap(one)(
                    ptabs, lengths, tok, drafts, temp, top_k, top_p, seed,
                    made,
                )
                dest = jnp.where(active[:, None], ptabs, TRASH_PAGE)
                new_pool = [
                    {k: pl[k].at[dest].set(pages[li][k]) for k in pl}
                    for li, pl in enumerate(pool_layers)
                ]
                # Budget / eos truncation — verbatim the linear scheme.
                n0 = a + 1
                n1 = jnp.minimum(n0, budget - made)
                idx = jnp.arange(S)[None, :]
                eos_in = (E == eos[:, None]) & (idx < n1[:, None])
                any_eos = eos_in.any(axis=1)
                first_eos = jnp.argmax(eos_in, axis=1)
                n_final = jnp.where(any_eos, first_eos + 1, n1)
                n_final = jnp.where(active, n_final, 0)
                new_lengths = lengths + n_final
                new_made = made + n_final
                rows = jnp.arange(E.shape[0])
                last = jnp.clip(n_final - 1, 0, S - 1)
                new_tok = jnp.where(active, E[rows, last], tok)
                finished = active & ((new_made >= budget) | any_eos)
                valid = (idx < n_final[:, None]) & active[:, None]
                accepted = jnp.where(active, jnp.minimum(a, n_final - 1), 0)
                return (
                    new_pool, active & ~finished, new_lengths, new_tok,
                    new_made, E.T, valid.T, accepted,
                )

            return tree_fn

        # Compiled program set, host-selected per call. Two sampling
        # variants of prefill and step: per-row top-k/top-p needs two
        # full-vocab XLA sorts per micro-step (per-row cutoffs defeat
        # lax.top_k's static k), and an all-greedy round (THE common
        # serving mix, and what the bench's sequential baseline pays) must
        # not pay them. Plus the speculative verify program for all-greedy
        # rounds when spec_k > 0. Still a fixed set: warmup compiles every
        # member, and the compile-count assert covers the lot.
        donate = (0,) if self.paged else ()
        self._prefill_greedy = self._jit_program(
            make_prefill(False), "prefill", donate
        )
        self._prefill_sampled = self._jit_program(
            make_prefill(True), "prefill", donate
        )
        step_donate = (0,) if self.paged else (1,)
        self._step_greedy = self._jit_program(
            make_step(False), "step", step_donate
        )
        self._step_sampled = self._jit_program(
            make_step(True), "step", step_donate
        )
        # Tree mode (spec_branches > 1) REPLACES the linear verify
        # programs — a round is either linear or tree for an engine's
        # whole lifetime, so the compiled set stays fixed either way.
        tree_mode = self.spec_k > 0 and self.spec_branches > 1
        self._spec = (
            self._jit_program(make_spec(rs=False), "spec", (0,))
            if self.spec_k and not tree_mode
            else None
        )
        # The rejection-sampling variant serves rounds with ANY sampled
        # lane (its `where` handles mixed greedy rows); the greedy variant
        # keeps all-greedy rounds free of the filter's full-vocab sorts.
        self._spec_rs = (
            self._jit_program(make_spec(rs=True), "spec", (0,))
            if self.spec_k and not tree_mode
            else None
        )
        self._tree = (
            self._jit_program(make_tree_spec(rs=False), "tree", (0,))
            if tree_mode
            else None
        )
        self._tree_rs = (
            self._jit_program(make_tree_spec(rs=True), "tree", (0,))
            if tree_mode
            else None
        )
        self._draft = (
            self._jit_program(
                build_draft_fn(draft_cfg, self.spec_k, self.draft_window),
                "draft",
                (),
            )
            if self.draft_params is not None
            else None
        )

    # -- program / pool hooks (overridden by ShardedSlotEngine) -----------

    def _build_pool(self, cfg, max_len, kv_pages):
        return PagedKVPool(
            cfg, self.slots, max_len, self.page_size, kv_pages
        )

    def _jit_program(self, fn, kind, donate):
        """Compile hook: the base engine jits on the default device; the
        sharded engine overrides this to jit the SAME program under its
        mesh with in/out shardings. ``kind`` names the fixed argument
        layout (``prefill``/``step``/``spec``/``tree``/``draft``)."""
        return jax.jit(fn, donate_argnums=donate)

    # -- slot lifecycle ---------------------------------------------------

    @property
    def free_slots(self) -> int:
        return self.pool.num_free

    @property
    def active_count(self) -> int:
        return int(self.active.sum())

    @property
    def prefilling_count(self) -> int:
        return int(self.prefilling.sum())

    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: ``prefill_len`` is the hard cap only
        when chunked prefill is off; with it on, any prompt that leaves
        room for one generated token fits (p + max_new <= max_len is
        validated separately)."""
        if self.paged and self.prefill_chunk_tokens > 0:
            return self.max_len - 1
        return self.prefill_len

    @property
    def pages_free(self) -> int | None:
        return self.pool.pages_free if self.paged else None

    @property
    def utilization(self) -> float:
        """Capacity in use, in the layout's native unit: PAGE occupancy
        under paging (the unit admission is actually gated on), slot
        occupancy for the monolithic layout."""
        return self.pool.occupancy

    @property
    def prefix_hit_rate(self) -> float:
        return self.prefix.hit_rate if self.prefix is not None else 0.0

    @property
    def spec_accept_rate(self) -> float:
        prop = self.stats["spec_drafts_proposed"]
        return self.stats["spec_drafts_accepted"] / prop if prop else 0.0

    def spec_accept_rate_for(self, drafter: str) -> float:
        prop = self.stats[f"spec_drafts_proposed_{drafter}"]
        acc = self.stats[f"spec_drafts_accepted_{drafter}"]
        return acc / prop if prop else 0.0

    @property
    def spec_accept_per_verify(self) -> float:
        """Mean accepted drafts per (slot, verify-round) — the quantity
        tree speculation exists to raise: a tree round costs one widened
        forward per slot exactly like a linear round costs one narrow one,
        so accepted-per-verify is the apples-to-apples speedup axis."""
        ver = self.stats["spec_verifies"]
        return self.stats["spec_drafts_accepted"] / ver if ver else 0.0

    @property
    def kv_dtype(self) -> str:
        """Live KV-cache element format: ``'int8'`` when the pool pages
        are quantize-on-write int8 rows + f32 scales
        (``cfg.kv_cache_dtype == 'int8'``), else ``'bf16'`` — the
        compute-dtype passthrough (f32 bytes under the CPU-smoke f32
        compute dtype; the label names the serving mode, not the literal
        storage width). Travels in handoff bundle headers and /healthz so
        tiers/routers can tell formats apart."""
        quant = getattr(self.cfg, "kv_cache_dtype", None)
        return "int8" if quant == "int8" else "bf16"

    @property
    def kv_bytes_per_token(self) -> float:
        """KV-cache bytes one token position costs across all layers in
        the live pool format (int8 rows carry their f32 scale overhead) —
        the byte-diet gauge ``bench_serving`` ratios int8 against bf16."""
        return self.pool.bytes_per_token

    def acquire_slot(self) -> int | None:
        return self.pool.alloc()

    def release(self, slot: int) -> None:
        self.active[slot] = False
        if self.prefilling[slot]:
            self.prefilling[slot] = False
            self._pf.pop(slot, None)
            try:
                self._pf_queue.remove(slot)
            except ValueError:
                pass
        self.pool.free(slot)

    def start(
        self,
        slot: int,
        prompt,
        *,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
        eos_id: int | None = None,
    ) -> tuple[int | None, bool]:
        """Prefill ``prompt`` into ``slot`` and sample its first token.

        Returns ``(first_token, finished)``; a request that is already done
        after one token (budget 1, or the first token is its eos) comes
        back ``finished=True`` and the caller releases the slot. Under
        paging, raises :class:`InsufficientPages` (slot untouched, no
        references leaked) when the pool cannot back the request even
        after evicting prefix-cache entries.

        When the post-adoption tail exceeds the chunk width (possible only
        with chunked prefill enabled), no forward runs here: the slot
        enters the PREFILLING phase, ``(None, False)`` is returned, and
        the first token surfaces from a later :meth:`step` once the final
        chunk lands (its row precedes that round's decode rows)."""
        prompt = np.asarray(prompt, np.int32).ravel()
        p = int(prompt.size)
        if p < 1:
            raise ValueError("prompt must contain at least one token")
        if p > self.max_prompt_len:
            raise ValueError(
                f"prompt length {p} > engine prefill_len {self.prefill_len}"
                if self.max_prompt_len == self.prefill_len
                else f"prompt length {p} > engine max prompt "
                     f"{self.max_prompt_len}"
            )
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if p + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {p} + {max_new_tokens} new > engine max_len "
                f"{self.max_len}"
            )
        sampled = temperature > 0.0
        prefill = self._prefill_sampled if sampled else self._prefill_greedy
        sargs = (
            np.float32(temperature), np.int32(top_k), np.float32(top_p),
            np.uint32(seed),
        )
        eos = -1 if eos_id is None else int(eos_id)
        if self.paged:
            first = self._start_paged(slot, prompt, p, max_new_tokens,
                                      prefill, sargs, sampled)
        else:
            padded = np.zeros((1, self.prefill_len), np.int32)
            padded[0, :p] = prompt
            new_layers, first = prefill(self.params, padded, np.int32(p), *sargs)
            self.pool.adopt(slot, new_layers)
        # Registers shared by both outcomes (immediate first token vs
        # PREFILLING): sampling params and limits are fixed at admission.
        self.temp[slot] = temperature
        self.top_k[slot] = top_k
        self.top_p[slot] = top_p
        self.seed[slot] = np.uint32(seed & 0xFFFFFFFF)
        self.budget[slot] = max_new_tokens
        self.eos[slot] = eos
        if first is None:
            # Chunked path scheduled by _start_paged; pages are all bound,
            # chunks spend across subsequent step() calls.
            self.active[slot] = False
            self.lengths[slot] = 0
            self.made[slot] = 0
            if self.spec_k:
                self.history[slot, :p] = prompt
                self.hist_len[slot] = p
            if self.sentinel is not None:
                self.sentinel.poll(self.compile_count())
            return None, False
        first = int(first)
        finished = max_new_tokens == 1 or first == eos
        self.active[slot] = not finished
        self.lengths[slot] = p
        self.cur_tok[slot] = first
        self.made[slot] = 1
        if self.spec_k:
            self.history[slot, :p] = prompt
            self.history[slot, p] = first
            self.hist_len[slot] = p + 1
        if self.sentinel is not None:
            self.sentinel.poll(self.compile_count())
        return first, finished

    def _start_paged(self, slot, prompt, p, max_new, prefill, sargs, sampled):
        """Page allocation + prefix adoption + tail prefill for one slot.
        Returns the first token, or ``None`` when the tail exceeds every
        bucket and a chunked-prefill plan was scheduled instead."""
        pool, ps = self.pool, self.page_size
        n_pages = pool.pages_needed(p, max_new)
        # Adoption cap: the tail must keep >= 1 real token (the first-
        # token logits come from position p-1). The per-bucket clamp below
        # additionally keeps the tail write under max_len.
        cap = (p - 1) // ps
        matched = self.prefix.match(prompt, cap) if self.prefix else []
        # Pick the narrowest compiled prefill width whose bucket holds the
        # post-adoption tail. Per bucket, adoption is clamped so the tail
        # write at offset m0 fits below max_len (dynamic_update_slice
        # would CLAMP the start down and corrupt adopted rows otherwise);
        # with chunking off the largest bucket (prefill_len, clamp
        # included) always fits since start() validated p <= prefill_len.
        # Adopted pages beyond the clamp are returned — their content is
        # simply recomputed by the (still narrower) tail forward. A tail
        # wider than every bucket (a long prompt under chunked prefill)
        # falls through to the chunk planner.
        m_pages = 0
        fits = False
        for width in self.prefill_buckets:
            m_pages = min(len(matched), (self.max_len - width) // ps)
            if p - m_pages * ps <= width:
                fits = True
                break
        if not fits:
            return self._start_chunked(slot, prompt, p, max_new, sargs,
                                       sampled, matched)
        for pid in matched[m_pages:]:
            pool.decref(pid)
        matched = matched[:m_pages]
        own = pool.alloc_pages(n_pages - len(matched))
        if own is None and self.prefix is not None:
            self.prefix.evict_for(n_pages - len(matched))
            own = pool.alloc_pages(n_pages - len(matched))
        if own is None:
            for pid in matched:
                pool.decref(pid)
            raise InsufficientPages(
                f"need {n_pages - len(matched)} pages, "
                f"{pool.pages_free} free (slot {slot}, prompt {p} + "
                f"{max_new} new @ page_size {ps})"
            )
        page_ids = matched + own
        pool.bind(slot, page_ids)
        m0 = len(matched) * ps
        # The forward consumes only the TAIL — positions below m0 are
        # covered by adopted pages; the padded tail lands at cache offset
        # m0 inside the program.
        padded = np.zeros((1, width), np.int32)
        padded[0, : p - m0] = prompt[m0:]
        row = np.array(pool.page_tables[slot])  # defensive copy for the jit
        new_pool, first = prefill(
            pool.layers, self.params, padded, np.int32(p), np.int32(m0),
            row, *sargs,
        )
        pool.layers = new_pool
        if self.prefix is not None:
            self.prefix.record_lookup(m0, p)
            self.prefix.insert(prompt, page_ids)
            self.stats["prefix_tokens_matched"] = self.prefix.tokens_matched
            self.stats["prefix_tokens_total"] = self.prefix.tokens_looked_up
        return first

    def _start_chunked(self, slot, prompt, p, max_new, sargs, sampled,
                       matched):
        """Bind every page up front and plan the chunk schedule; no
        forward runs here. The plan is a list of ``(offset, width,
        is_final)`` bucket-program calls: full-chunk-width intermediates
        (sampled token discarded), then ONE suffix-aligned final chunk —
        its fed window ends at position ``p-1`` so the first-token logits
        come from the true last prompt position, with no padding anywhere.

        Adoption is capped so the post-adoption remainder strictly
        exceeds the chunk width: that forces >= 1 intermediate chunk,
        which keeps the final chunk's window start ``p - w`` strictly
        above the adopted boundary — the final forward only ever REwrites
        the slot's own pages (overlap recompute is deterministic and
        write-before-attend makes it safe), never a shared prefix page."""
        pool, ps, c = self.pool, self.page_size, self.prefill_chunk_tokens
        n_pages = pool.pages_needed(p, max_new)
        a = min(len(matched), max(0, (p - c - 1) // ps))
        for pid in matched[a:]:
            pool.decref(pid)
        matched = matched[:a]
        own = pool.alloc_pages(n_pages - len(matched))
        if own is None and self.prefix is not None:
            self.prefix.evict_for(n_pages - len(matched))
            own = pool.alloc_pages(n_pages - len(matched))
        if own is None:
            for pid in matched:
                pool.decref(pid)
            raise InsufficientPages(
                f"need {n_pages - len(matched)} pages, "
                f"{pool.pages_free} free (slot {slot}, prompt {p} + "
                f"{max_new} new @ page_size {ps}, chunked)"
            )
        page_ids = matched + own
        pool.bind(slot, page_ids)
        m0 = len(matched) * ps
        chunks = []
        m = m0
        while p - m > c:
            chunks.append((m, c, False))
            m += c
        r = p - m  # 1 <= r <= c: the suffix the final chunk must cover
        w = next(b for b in self.prefill_buckets if b >= r)
        chunks.append((p - w, w, True))
        self._pf[slot] = {
            "slot": slot, "prompt": prompt, "p": p, "chunks": chunks,
            "idx": 0, "sampled": sampled, "sargs": sargs,
            "page_ids": page_ids, "m0": m0,
        }
        self.prefilling[slot] = True
        self._pf_queue.append(slot)
        if self.prefix is not None:
            self.prefix.record_lookup(m0, p)
            self.stats["prefix_tokens_matched"] = self.prefix.tokens_matched
            self.stats["prefix_tokens_total"] = self.prefix.tokens_looked_up
        return None

    def _advance_prefill(self):
        """Spend up to ``prefill_chunk_tokens`` of prefill this iteration
        (always >= 1 chunk when any slot is PREFILLING — forward progress
        is unconditional), round-robin across prefilling slots. Returns
        ``(events, spent)`` where events are ``(slot, first_token,
        finished)`` for slots whose FINAL chunk landed this call."""
        events = []
        spent = 0
        chunks_run = 0
        budget = self.prefill_chunk_tokens
        while self._pf_queue:
            slot = self._pf_queue[0]
            st = self._pf[slot]
            m, w, final = st["chunks"][st["idx"]]
            if spent and spent + w > budget:
                break
            first = self._run_chunk(st, m, w, final)
            spent += w
            chunks_run += 1
            st["idx"] += 1
            if final:
                self._pf_queue.popleft()
                del self._pf[slot]
                self.prefilling[slot] = False
                prompt, p = st["prompt"], st["p"]
                eos = int(self.eos[slot])
                finished = int(self.budget[slot]) == 1 or first == eos
                self.active[slot] = not finished
                self.lengths[slot] = p
                self.cur_tok[slot] = first
                self.made[slot] = 1
                if self.spec_k:
                    self.history[slot, p] = first
                    self.hist_len[slot] = p + 1
                if self.prefix is not None:
                    # Pages only become adoptable once every position is
                    # filled — insert at completion, not at start().
                    self.prefix.insert(prompt, st["page_ids"])
                events.append((slot, first, finished))
            else:
                self._pf_queue.rotate(-1)
        self.stats["prefill_chunks"] += chunks_run
        self.stats["prefill_tokens_last_iter"] = spent
        return events, spent

    def _run_chunk(self, st, m, w, final):
        """One bucket-program call of the chunk plan for one slot: ``w``
        REAL tokens at offset ``m`` (cache resumes at len = m; the
        program's scatter-back writes every page in the row, where pages
        below the chunk round-trip their gathered values). Intermediate
        chunks discard the sampled token; the final chunk's is the
        request's first token."""
        pool = self.pool
        prompt, p = st["prompt"], st["p"]
        toks = np.ascontiguousarray(prompt[m : m + w][None])
        row = np.array(pool.page_tables[st["slot"]])
        prefill = (
            self._prefill_sampled
            if final and st["sampled"]
            else self._prefill_greedy
        )
        length = np.int32(p if final else m + w)
        new_pool, first = prefill(
            pool.layers, self.params, toks, length, np.int32(m), row,
            *st["sargs"],
        )
        pool.layers = new_pool
        return int(first) if final else None

    def step(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One batch round over every slot.

        Returns ``(tokens (k, slots) int32, valid (k, slots) bool,
        done (slots,) bool)`` — ``k`` is ``steps_per_sync`` for plain
        rounds and ``spec_k + 1`` for speculative rounds (callers already
        iterate rows under the valid mask, so the burst size is opaque to
        them). ``done`` marks slots that finished during this round — the
        caller collects their output and ``release``s them, which is what
        lets the NEXT round admit replacements (iteration-level
        batching).

        With chunked prefill in flight, each call first spends one
        iteration's prefill budget (PREFILLING slots advance one or more
        chunks), then runs the normal decode round over the ACTIVE slots
        — long prefills never stall co-resident decodes. A slot whose
        final chunk lands this call contributes its first token as one
        extra LEADING row and joins the same call's decode round."""
        if not self.active.any() and not self.prefilling.any():
            raise RuntimeError("step() with no active slots")
        pre_events, _ = self._advance_prefill()
        if self.active.any():
            toks, valid, done = self._decode_round()
        else:
            toks = np.zeros((0, self.slots), np.int32)
            valid = np.zeros((0, self.slots), bool)
            done = np.zeros(self.slots, bool)
            if self.sentinel is not None:
                self.sentinel.poll(self.compile_count())
        if pre_events:
            row_t = np.zeros((1, self.slots), np.int32)
            row_v = np.zeros((1, self.slots), bool)
            for slot, first, finished in pre_events:
                row_t[0, slot] = first
                row_v[0, slot] = True
                if finished:
                    done[slot] = True
            toks = np.concatenate([row_t, toks])
            valid = np.concatenate([row_v, valid])
        return toks, valid, done

    def _decode_round(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        # The sampled program handles greedy rows correctly (via `where`),
        # so a mixed batch runs sampled; only an all-greedy batch takes the
        # sort-free fast path (and, when enabled, the speculative one).
        any_sampled = bool((self.temp[self.active] > 0.0).any())
        if (
            self.spec_k
            and not self._force_plain
            # Verify writes the whole fed block above each slot's length
            # (spec_k+1 linear, 1+B*spec_k tree); a slot within that of
            # max_len would clamp the write — fall back to plain rounds
            # for that (rare, end-of-window) round.
            and bool(
                (self.lengths[self.active] + self._spec_write
                 <= self.max_len).all()
            )
        ):
            return self._spec_round(any_sampled)
        self.stats["plain_rounds"] += 1
        step = self._step_sampled if any_sampled else self._step_greedy
        if self.paged:
            out = step(
                self.pool.layers, self.params, self.pool.page_tables,
                self.active, self.lengths, self.cur_tok, self.temp,
                self.top_k, self.top_p, self.seed, self.made, self.budget,
                self.eos,
            )
        else:
            out = step(
                self.params, self.pool.layers, self.active, self.lengths,
                self.cur_tok, self.temp, self.top_k, self.top_p, self.seed,
                self.made, self.budget, self.eos,
            )
        layers, active, lengths, tok, made, toks, valid = out
        return self._finish_round(layers, active, lengths, tok, made,
                                  toks, valid)

    def _spec_round(
        self, any_sampled: bool = False
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        if self.spec_branches > 1:
            drafts = self._propose_tree_drafts()
            spec = self._tree_rs if any_sampled else self._tree
        else:
            drafts = self._propose_drafts()
            spec = self._spec_rs if any_sampled else self._spec
        out = spec(
            self.pool.layers, self.params, self.pool.page_tables,
            self.active, self.lengths, self.cur_tok, drafts, self.temp,
            self.top_k, self.top_p, self.seed, self.made, self.budget,
            self.eos,
        )
        layers, active, lengths, tok, made, toks, valid, accepted = out
        n_act = int(self.active.sum())
        # "Proposed" counts the acceptable path budget (spec_k per slot)
        # in BOTH modes, so accept-rate stays comparable between linear
        # and tree rounds; the tree's extra branches only buy a better
        # chance of a long path, never more accepted tokens per verify.
        proposed = n_act * self.spec_k
        acc_arr = np.asarray(accepted)
        accepted_n = int(acc_arr.sum())
        self.accept_samples.extend(int(x) for x in acc_arr[self.active])
        self.stats["spec_rounds"] += 1
        self.stats["spec_verifies"] += n_act
        if any_sampled:
            self.stats["spec_rounds_sampled"] += 1
        self.stats["spec_drafts_proposed"] += proposed
        self.stats["spec_drafts_accepted"] += accepted_n
        self.stats[f"spec_drafts_proposed_{self.drafter}"] += proposed
        self.stats[f"spec_drafts_accepted_{self.drafter}"] += accepted_n
        return self._finish_round(layers, active, lengths, tok, made,
                                  toks, valid)

    def _propose_drafts(self) -> np.ndarray:
        """(slots, spec_k) draft tokens for the active lanes: the learned
        draft model when loaded (one jitted call over every lane — the
        cur_tok is the LAST history entry, so the draft's first output is
        its prediction for the token after it), else the host n-gram
        prompt-lookup fallback. Inactive lanes draft from a length-1 dummy
        window; the verify masks them out."""
        drafts = np.zeros((self.slots, self.spec_k), np.int32)
        if self._draft is not None:
            W = self.draft_window
            toks = np.zeros((self.slots, W), np.int32)
            lens = np.ones(self.slots, np.int32)
            pos0 = np.zeros(self.slots, np.int32)
            for s in np.nonzero(self.active)[0]:
                n = int(self.hist_len[s])
                l = min(n, W)
                toks[s, :l] = self.history[s, n - l : n]
                lens[s] = max(l, 1)
                # Absolute position of toks[s, 0]: the drafter reads the
                # target's own pos_embed/RoPE at the true offsets.
                pos0[s] = n - l
            return np.asarray(
                self._draft(self.draft_params, toks, lens, pos0))
        for s in np.nonzero(self.active)[0]:
            drafts[s] = propose_ngram_drafts(
                self.history[s, : int(self.hist_len[s])], self.spec_k
            )
        return drafts

    def _propose_tree_drafts(self) -> np.ndarray:
        """(slots, spec_branches, spec_k) draft tree per slot. Branch 0 is
        EXACTLY :meth:`_propose_drafts`'s row (the linear drafter — learned
        or n-gram — which is what makes the tree's accepted-per-verify
        dominate the linear baseline pointwise); branches 1.. come from
        ``propose_ngram_tree`` over the slot's own history PLUS every other
        active slot's history — the batch-wide shared draft pool. Slots
        without enough distinct candidates repeat a filled branch, which
        the verify treats as a duplicate (harmless)."""
        B, D = self.spec_branches, self.spec_k
        tree = np.zeros((self.slots, B, D), np.int32)
        tree[:, 0, :] = self._propose_drafts()
        if B > 1:
            act = np.nonzero(self.active)[0]
            hists = {
                s: self.history[s, : int(self.hist_len[s])] for s in act
            }
            for s in act:
                alt = propose_ngram_tree(
                    hists[s], D, B,
                    extra_histories=[hists[o] for o in act if o != s],
                )
                tree[s, 1:, :] = alt[1:]
        return tree

    def _finish_round(self, layers, active, lengths, tok, made, toks, valid):
        self.pool.layers = layers
        was_active = self.active
        # np.array (copy), not np.asarray: zero-copy views of jax buffers
        # are read-only, and start()/release() write these registers.
        self.active = np.array(active)
        self.lengths = np.array(lengths)
        self.cur_tok = np.array(tok)
        self.made = np.array(made)
        done = was_active & ~self.active
        toks = np.asarray(toks)
        valid = np.asarray(valid)
        if self.spec_k:
            for s in np.nonzero(was_active)[0]:
                emitted = toks[valid[:, s], s]
                n = int(self.hist_len[s])
                self.history[s, n : n + emitted.size] = emitted
                self.hist_len[s] = n + emitted.size
        if self.sentinel is not None:
            self.sentinel.poll(self.compile_count())
        return toks, valid, done

    # -- warmup / zero-recompile accounting -------------------------------

    def warmup(self) -> int:
        """Compile the full program set on throwaway requests; returns
        :meth:`compile_count`. Run this before taking traffic — after it,
        the count must never grow (the serving equivalent of
        ``__graft_entry__``'s collective-count asserts; asserted under
        churn in ``tests/test_serve_engine.py``). Covers: greedy prefill +
        PLAIN greedy step (forced even when speculation is on — the spec
        path falls back to it near max_len), BOTH speculative verify
        variants (greedy and rejection-sampling; the greedy pass also
        compiles the learned-draft program when one is loaded), the
        sampled prefill/step pair (the plain sampled step forced the
        same way when speculation is on), every prefill bucket width,
        and — when chunked prefill can trigger — one chunked prompt
        driven to completion (chunk calls reuse the bucket programs, so
        this compiles nothing new; it asserts that)."""
        passes: list[dict] = [{"temperature": 0.0, "_plain": True}]
        if self.spec_k:
            passes.append({"temperature": 0.0})
            # Sampled lanes take the spec path too (rejection-sampling
            # verify), so the plain sampled step needs its own forced
            # pass — it still serves the end-of-window fallback rounds.
            passes.append(
                {"temperature": 1.0, "top_k": 2, "top_p": 0.9,
                 "_plain": True}
            )
        passes.append({"temperature": 1.0, "top_k": 2, "top_p": 0.9})
        for kwargs in passes:
            force = kwargs.pop("_plain", False)
            slot = self.acquire_slot()
            if slot is None:
                raise RuntimeError("warmup needs a free slot")
            self._force_plain = force
            try:
                _, finished = self.start(
                    slot, [0], max_new_tokens=2, seed=0, **kwargs
                )
                if not finished:
                    while self.active[slot]:
                        self.step()
                    self.active[slot] = False
            finally:
                self._force_plain = False
                self.release(slot)
        # The passes above prefilled through the SMALLEST bucket (p=1);
        # compile the remaining widths too — a length-b throwaway prompt
        # forces bucket b exactly, and max_new=1 finishes at start() so
        # only the prefill programs are exercised. Adoption is disabled
        # for these passes: the greedy pass would otherwise insert its
        # [0]*width pages and the identical SAMPLED prompt would adopt
        # them and prefill through a smaller tail bucket, leaving the
        # full-width sampled prefill uncompiled (first sampled
        # prefill_len-wide prompt in traffic would then recompile).
        prefix, self.prefix = self.prefix, None
        try:
            for width in self.prefill_buckets[1:]:
                p_warm = min(width, self.max_len - 1)
                for kwargs in ({}, {"temperature": 1.0, "top_k": 2}):
                    slot = self.acquire_slot()
                    try:
                        self.start(slot, [0] * p_warm, max_new_tokens=1,
                                   seed=0, **kwargs)
                    finally:
                        self.release(slot)
        finally:
            self.prefix = prefix
        if self.paged and 0 < self.prefill_chunk_tokens < self.max_len - 1:
            # One chunked prompt per sampling variant, driven through
            # step() to completion (budget 1 finishes at the final chunk).
            p_long = min(self.prefill_chunk_tokens + 1, self.max_len - 1)
            for kwargs in ({}, {"temperature": 1.0, "top_k": 2}):
                slot = self.acquire_slot()
                try:
                    self.start(slot, [0] * p_long, max_new_tokens=1,
                               seed=0, **kwargs)
                    while self.prefilling[slot]:
                        self.step()
                finally:
                    self.release(slot)
        if self.prefix is not None:
            # Warmup's throwaway prompts must not linger as adoptable
            # prefixes (or skew the hit-rate counters).
            self.prefix.clear()
            self.prefix.tokens_matched = 0
            self.prefix.tokens_looked_up = 0
            self.stats["prefix_tokens_matched"] = 0
            self.stats["prefix_tokens_total"] = 0
        n = self.compile_count()
        if self.sentinel is not None:
            # Sync the poll base to the warmed cache size, then draw the
            # warm line: any compile the sentinel sees from here on counts
            # as recompile_events_total (the SLO-alerting condition).
            self.sentinel.poll(n)
            self.sentinel.mark_warm()
        return n

    def compile_count(self) -> int:
        """Total compiled programs across the engine's jitted callables —
        stable after :meth:`warmup` or something is shape-unstable."""
        fns = [self._prefill_greedy, self._prefill_sampled,
               self._step_greedy, self._step_sampled]
        if self._spec is not None:
            fns.append(self._spec)
        if self._spec_rs is not None:
            fns.append(self._spec_rs)
        if self._tree is not None:
            fns.append(self._tree)
        if self._tree_rs is not None:
            fns.append(self._tree_rs)
        if self._draft is not None:
            fns.append(self._draft)
        own = sum(
            f._cache_size() if hasattr(f, "_cache_size") else 0 for f in fns
        )
        return own + self.pool.compile_count()

    @property
    def mesh_device_count(self) -> int:
        """Devices the engine's programs span: 1 for the replicated base
        engine, ``mesh.size`` for the sharded one. Routers use this (via
        ``/healthz``) to tell one tp-wide replica from N independent ones."""
        return int(self.mesh.size) if self.mesh is not None else 1

    @property
    def hbm_bytes_per_device(self) -> int:
        """KV pool bytes RESIDENT per device. The sharded engine splits
        the pool's kv-head axis ``tp`` ways; everything else about the
        pool (page tables, accounting) is host-side and free."""
        return int(self.pool.hbm_bytes) // max(1, self.tp)

    @property
    def weight_dtype(self) -> str:
        """Weight quantization mode serving this replica: ``'int8'`` /
        ``'int4'`` (``models/quant.py`` trees) or ``'native'`` for the
        stored high-precision weights. Surfaced through ``/healthz`` and
        the fleet registry so the router can tell variants apart."""
        return getattr(self.cfg, "weight_dtype", None) or "native"

    @property
    def draft_weight_dtype(self) -> str:
        """Quantization mode of the learned drafter (``''`` when the
        engine runs the host n-gram drafter — it has no weights). The
        issue contract quantizes the drafter HARDER than the target
        (int4 drafter over int8 target); this label lets dashboards
        verify that pairing per replica."""
        if self.draft_cfg is None:
            return ""
        return getattr(self.draft_cfg, "weight_dtype", None) or "native"

    @property
    def weight_bytes_per_device(self) -> int:
        """Target-model weight bytes RESIDENT per device (the drafter is
        accounted separately — it is small by construction). For sharded
        leaves the per-device share is the mean addressable-shard size
        (each mesh device holds exactly one shard: a split leaf counts
        ``nbytes/tp``, a replicated one full ``nbytes``)."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.params):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                total += sum(sh.data.nbytes for sh in shards) // len(shards)
            else:
                total += leaf.nbytes
        return int(total)

    # -- weight hot-swap (serve/deploy/) -----------------------------------
    #
    # ``self.params`` is a per-call traced argument to every jitted program
    # and is NEVER in a donate_argnums set (prefill donates the KV operand,
    # step donates the pool layers) — so replacing the reference between
    # rounds is donation-safe, and as long as the candidate matches the
    # live tree's structure/shapes/dtypes the jit signatures are unchanged:
    # zero recompiles by construction, which the RecompileSentinel then
    # asserts empirically.

    def check_swap_compatible(self, candidate) -> None:
        """Raise ``ValueError`` unless ``candidate`` has the live param
        tree's exact treedef, leaf shapes, and leaf dtypes — the validated
        precondition for a zero-recompile swap. Called before any device
        transfer so a wrong-architecture checkpoint is rejected for free."""
        cur, cur_def = jax.tree_util.tree_flatten(self.params)
        new, new_def = jax.tree_util.tree_flatten(candidate)
        if cur_def != new_def:
            raise ValueError(
                "adopt_weights: candidate tree structure differs from the "
                f"serving tree ({new_def} vs {cur_def})"
            )
        for i, (a, b) in enumerate(zip(cur, new)):
            if tuple(np.shape(a)) != tuple(np.shape(b)):
                raise ValueError(
                    f"adopt_weights: leaf {i} shape {np.shape(b)} != "
                    f"serving {np.shape(a)}"
                )
            da = jnp.asarray(a).dtype if not hasattr(a, "dtype") else a.dtype
            db = jnp.result_type(b)
            if np.dtype(da) != np.dtype(db):
                raise ValueError(
                    f"adopt_weights: leaf {i} dtype {db} != serving {da} "
                    "(a dtype change is a different jit signature — "
                    "recompile — so it must ship as a new replica, not a "
                    "hot swap)"
                )

    def _place_params(self, candidate):
        """Device placement for a swap candidate: plain device_put here;
        the sharded engine routes through its SERVE_TP_RULES shardings."""
        return jax.device_put(candidate)

    def stage_weights(self, candidate):
        """Validate + place a candidate param tree on the engine's devices
        WITHOUT touching the live reference — the double-buffer half of a
        hot swap. Safe to call from a watcher thread while the driver
        thread keeps decoding on the old buffers (the transfer allocates
        fresh buffers; nothing donates params). Returns the staged tree."""
        self.check_swap_compatible(candidate)
        return self._place_params(candidate)

    def adopt_weights(self, candidate, *, version=None, variant=None):
        """Flip the live param reference to ``candidate`` and return the
        previous tree (the rollback buffer). MUST be called between engine
        rounds on the driver thread — the scheduler's iteration boundary —
        so no jitted program is mid-flight on either buffer set. In-flight
        slots simply continue on the new weights next round; their KV
        cache carries over (same architecture by the precondition)."""
        candidate = self.stage_weights(candidate)
        prev, self.params = self.params, candidate
        if version is not None:
            self.weight_version = int(version)
        if variant is not None:
            self.serving_variant = str(variant)
        return prev

    # -- slot handoff (prefill tier -> decode tier) ------------------------
    #
    # Disaggregated serving moves a slot BETWEEN engines after prefill:
    # the prefill tier runs (possibly chunked) prefill to completion, then
    # exports the slot's KV pages plus the per-slot host registers; the
    # decode tier imports them and continues decoding. Token parity is by
    # construction: every sampling key is ``fold_in(PRNGKey(seed), made)``
    # and the registers travel exactly, so the continuation is the same
    # token stream local decode would have produced. Export gathers pages
    # eagerly and import scatters them eagerly + rebinds the (host numpy)
    # page table — no new jitted program on either side, so the
    # zero-recompile contract holds on both tiers.

    def export_slot(self, slot: int, *, history=None) -> dict:
        """Capture ``slot``'s decode state as a host-serializable bundle.

        The slot must be post-prefill and still active (a request that
        finished at its first token has nothing to hand off). ``history``
        (prompt + emitted tokens) feeds the importing engine's drafter;
        when the exporter tracks history itself (``spec_k > 0``) its own
        register wins. The slot stays live here — the caller releases it
        only once the peer acknowledged the import (fallback to local
        decode otherwise, so no request is ever lost)."""
        if not self.paged:
            raise RuntimeError("slot handoff requires the paged KV layout")
        if self.prefilling[slot]:
            raise RuntimeError(f"slot {slot} is mid-chunked-prefill")
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if self.spec_k:
            history = self.history[slot, : int(self.hist_len[slot])]
        hist = (np.asarray(history, np.int32).ravel().tolist()
                if history is not None else [])
        return {
            "length": int(self.lengths[slot]),
            "cur_tok": int(self.cur_tok[slot]),
            "made": int(self.made[slot]),
            "budget": int(self.budget[slot]),
            "eos": int(self.eos[slot]),
            "temperature": float(self.temp[slot]),
            "top_k": int(self.top_k[slot]),
            "top_p": float(self.top_p[slot]),
            "seed": int(self.seed[slot]),
            "history": hist,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "pages": self.pool.export_pages(slot),
        }

    def export_slot_meta(self, slot: int, *, history=None) -> dict:
        """The v2 (streaming) flavor of :meth:`export_slot`: identical
        registers, but the page leaves come from
        ``pool.snapshot_pages`` — device arrays whose gathers were only
        DISPATCHED. The driver thread pays microseconds of op dispatch
        instead of the whole device->host copy; the outbox worker pulls
        rows to host chunk by chunk while streaming. Same preconditions
        and the same exporter-keeps-the-slot contract as
        :meth:`export_slot`."""
        if not self.paged:
            raise RuntimeError("slot handoff requires the paged KV layout")
        if self.prefilling[slot]:
            raise RuntimeError(f"slot {slot} is mid-chunked-prefill")
        if not self.active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        if self.spec_k:
            history = self.history[slot, : int(self.hist_len[slot])]
        hist = (np.asarray(history, np.int32).ravel().tolist()
                if history is not None else [])
        return {
            "length": int(self.lengths[slot]),
            "cur_tok": int(self.cur_tok[slot]),
            "made": int(self.made[slot]),
            "budget": int(self.budget[slot]),
            "eos": int(self.eos[slot]),
            "temperature": float(self.temp[slot]),
            "top_k": int(self.top_k[slot]),
            "top_p": float(self.top_p[slot]),
            "seed": int(self.seed[slot]),
            "history": hist,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "pages": self.pool.snapshot_pages(slot),
        }

    def import_slot(self, slot: int, bundle: dict) -> None:
        """Adopt an exported slot bundle into a freshly acquired ``slot``.

        Raises :class:`InsufficientPages` (slot registers untouched — the
        caller releases the slot and retries or tells the exporter to
        decode locally) when the pool cannot back the payload. On success
        the slot is active and the next :meth:`step` continues the
        request exactly where the exporter stopped."""
        self.validate_handoff_header(bundle)
        self.pool.import_pages(slot, bundle["pages"])
        self._adopt_handoff_registers(slot, bundle)

    def adopt_imported_slot(self, slot: int, bundle: dict,
                            page_ids) -> None:
        """Commit a STAGED (chunk-streamed) import: ``page_ids`` were
        already allocated and scattered incrementally; bind them to
        ``slot`` and adopt the bundle's registers. The registers-only
        counterpart of :meth:`import_slot` — the all-or-nothing contract
        holds because nothing is bound or activated until this call, and
        the abort path frees the staged pages without touching a slot."""
        self.validate_handoff_header(bundle)
        self.pool.bind(slot, list(page_ids))
        self._adopt_handoff_registers(slot, bundle)

    def validate_handoff_header(self, bundle: dict) -> None:
        """Typed pre-import validation (page geometry, KV format, length
        headroom) — shared by the monolithic and staged import paths, and
        cheap enough for a receiver to run BEFORE reading page bytes."""
        if not self.paged:
            raise RuntimeError("slot handoff requires the paged KV layout")
        if bundle["page_size"] != self.page_size:
            raise ValueError(
                f"handoff page_size {bundle['page_size']} != engine "
                f"page_size {self.page_size}"
            )
        # KV format must match EXACTLY: the pool's import scatters raw
        # rows into its own leaves by name, so an int8 bundle landing in a
        # bf16 pool (or vice versa) would silently cast rows without their
        # scales — garbage KV, not an error. A typed ValueError here takes
        # the scheduler's existing "invalid" fallback instead (exporter
        # decodes locally; no request lost, no silent dequant). Absent key
        # = pre-PR-14 exporter: permissive, formats were implicitly equal.
        kd = str(bundle.get("kv_dtype", "") or "")
        if kd and kd != self.kv_dtype:
            raise ValueError(
                f"handoff kv_dtype {kd!r} != engine kv_dtype "
                f"{self.kv_dtype!r}"
            )
        length = int(bundle["length"])
        headroom = int(bundle["budget"]) - int(bundle["made"])
        if length + headroom > self.max_len:
            raise ValueError(
                f"handoff length {length} + {headroom} remaining > engine "
                f"max_len {self.max_len}"
            )

    def _adopt_handoff_registers(self, slot: int, bundle: dict) -> None:
        self.active[slot] = True
        self.prefilling[slot] = False
        self.lengths[slot] = int(bundle["length"])
        self.cur_tok[slot] = int(bundle["cur_tok"])
        self.temp[slot] = float(bundle["temperature"])
        self.top_k[slot] = int(bundle["top_k"])
        self.top_p[slot] = float(bundle["top_p"])
        self.seed[slot] = np.uint32(int(bundle["seed"]) & 0xFFFFFFFF)
        self.made[slot] = int(bundle["made"])
        self.budget[slot] = int(bundle["budget"])
        self.eos[slot] = int(bundle["eos"])
        if self.spec_k:
            hist = np.asarray(bundle.get("history", ()), np.int32).ravel()
            hist = hist[: self.max_len]
            self.history[slot, : hist.size] = hist
            self.hist_len[slot] = hist.size
        if self.sentinel is not None:
            self.sentinel.poll(self.compile_count())


class ShardedSlotEngine(SlotEngine):
    """The SlotEngine on a TP-partitioned model — same slot API, same
    host-side registers and page tables, same fixed compiled-program set,
    but every program is jitted under a ``('data', 'model')`` mesh
    (``data`` axis size 1 — serving parallelism is slots, not batch):

    * **Weights** are placed by the declarative rule table
      (``parallel/rules.py::SERVE_TP_RULES`` unless ``rules=`` overrides):
      fused qkv / mlp_in column-parallel, proj / mlp_out row-parallel,
      embeddings + norms + lm_head replicated. ``in_shardings`` pin the
      same placement at every program boundary so donated buffers round-trip
      without resharding.
    * **KV pool** leaves shard along the kv-head axis
      (``P(None, 'model')`` — pages and in-page positions stay whole), the
      axis GQA-under-TP already constrains to ``num_kv_heads % tp == 0``.
    * **Everything host-side stays host-side and replicated**: page
      tables, slot registers, token buffers enter as numpy traced operands
      exactly as before, so rebinding pages never retraces and the
      zero-recompile-after-warmup contract (RecompileSentinel) is
      unchanged.

    GSPMD jit semantics make this a PLACEMENT change, not a numerics
    rewrite: XLA partitions the matmuls along the annotated dims and
    inserts the collectives, and the emitted TOKENS are identical to the
    single-device engine (asserted by the sharded_serve parity tests and
    in ``bench_serving_sharded``). Requires the paged KV layout.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        tp: int,
        devices=None,
        rules=None,
        **kw,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tensorflow_tpu.config import validate_tp_mesh
        from distributed_tensorflow_tpu.parallel.mesh import make_mesh
        from distributed_tensorflow_tpu.parallel.rules import (
            SERVE_TP_RULES,
            shardings_from_rules,
        )

        tp = int(tp)
        if tp < 2:
            raise ValueError(
                f"ShardedSlotEngine is the tp >= 2 path, got tp={tp}; "
                "use SlotEngine for a single-device replica"
            )
        validate_tp_mesh(cfg, tp)
        if getattr(cfg, "weight_dtype", None):
            from distributed_tensorflow_tpu.models.quant import (
                validate_weight_quant,
            )

            # TP adds a constraint config-time validation can't know: the
            # row-parallel int4 shards must hold whole scale groups.
            validate_weight_quant(
                cfg.weight_dtype, cfg.quant_group_size, cfg.d_model,
                cfg.d_ff, tp=tp,
            )
        page_size = kw.get("page_size")
        if page_size is not None and page_size <= 0:
            raise ValueError(
                "ShardedSlotEngine requires the paged KV layout "
                f"(page_size > 0), got page_size={page_size}"
            )
        devices = list(devices) if devices is not None else list(jax.devices())
        if len(devices) < tp:
            raise ValueError(
                f"tp={tp} needs {tp} devices but only {len(devices)} are "
                "visible (CPU smoke: set XLA_FLAGS="
                "--xla_force_host_platform_device_count)"
            )
        # Set BEFORE delegating: the base __init__ calls the _build_pool /
        # _jit_program hooks below, which read the mesh state.
        self.tp = tp
        self.mesh = make_mesh(
            num_devices=tp, model_parallel=tp, devices=devices[:tp]
        )
        self._rep = NamedSharding(self.mesh, P())
        # One spec covers every pool leaf: axis 1 is kv heads on both the
        # (pages, kv, ps, dh) k/v rows and the (pages, kv, ps) int8 scales;
        # unnamed trailing dims are replicated.
        self._kv_shard = NamedSharding(self.mesh, P(None, "model"))
        self._rules = tuple(rules) if rules is not None else SERVE_TP_RULES
        self._param_sh = shardings_from_rules(self._rules, params, self.mesh)
        params = jax.device_put(params, self._param_sh)
        super().__init__(cfg, params, **kw)

    # -- hooks -------------------------------------------------------------

    def _build_pool(self, cfg, max_len, kv_pages):
        return PagedKVPool(
            cfg, self.slots, max_len, self.page_size, kv_pages,
            kv_sharding=self._kv_shard,
        )

    def _place_params(self, candidate):
        # Swap candidates stage through the SAME rule-table shardings as
        # the boot-time params, so the jitted programs' in_shardings keep
        # matching and the flip stays resharding- and recompile-free.
        return jax.device_put(candidate, self._param_sh)

    def _jit_program(self, fn, kind, donate):
        """Jit under the mesh with explicit in/out shardings per program
        kind. Arg layouts are the paged ones (position 0 = pool layers,
        position 1 = params, everything after is a replicated host
        register); the pool position takes ONE sharding as a pytree
        prefix for all its leaves."""
        rep, kvs, psh = self._rep, self._kv_shard, self._param_sh
        if kind == "draft":
            # The drafter is a small replicated model over host windows —
            # nothing sharded flows through it.
            return jax.jit(fn, donate_argnums=donate)
        if kind == "prefill":
            # (pool, params, tokens, length, prefix_len, row, temp,
            #  top_k, top_p, seed) -> (pool, first)
            ins = (kvs, psh) + (rep,) * 8
            outs = (kvs, rep)
        elif kind == "step":
            # (pool, params, ptabs, active, lengths, tok, temp, top_k,
            #  top_p, seed, made, budget, eos)
            #   -> (pool, active, lengths, tok, made, toks, valid)
            ins = (kvs, psh) + (rep,) * 11
            outs = (kvs,) + (rep,) * 6
        elif kind in ("spec", "tree"):
            # (pool, params, ptabs, active, lengths, tok, drafts, temp,
            #  top_k, top_p, seed, made, budget, eos) -> (pool, active,
            #  lengths, tok, made, emitted.T, valid.T, accepted). The tree
            #  verify has the same layout — drafts is (slots, B, D)
            #  instead of (slots, k), still one replicated host operand.
            ins = (kvs, psh) + (rep,) * 12
            outs = (kvs,) + (rep,) * 7
        else:  # pragma: no cover - new kinds must be wired explicitly
            raise ValueError(f"unknown program kind {kind!r}")
        return jax.jit(
            fn, donate_argnums=donate, in_shardings=ins, out_shardings=outs
        )
