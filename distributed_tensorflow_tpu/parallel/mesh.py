"""Device-mesh construction.

Replaces the reference's ``tf.train.ClusterSpec`` + ``replica_device_setter``
placement model (``demo2/train.py:18-29``): instead of pinning variables to
parameter-server processes and ops to worker processes, all devices form a
``jax.sharding.Mesh``; parameters are replicated (or sharded) across it and
XLA inserts ICI collectives where shardings demand.

Axis conventions (room for every strategy even though the reference only
exercises DP — SURVEY §2.3):
  * ``data``  — batch (data-parallel) axis
  * ``model`` — tensor-parallel axis (optional second mesh dim)
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def unit_mesh_init(init_fn, *args):
    """Run a parameter-init function inside a trivial 1×1×1
    ('data','pipe','model') shard_map on one LOCAL device and return host
    numpy — the standard way to get GLOBAL-shape params for modules that
    query ``lax.axis_size`` (TP/MoE). All three framework axis names are
    bound (each size 1) so a module parameterized on ANY of them — e.g.
    ``ep_axis='pipe'`` — initializes without an unbound-axis error.
    The shard_map is jitted as a whole: eager shard_map dispatches every
    primitive as its own program, which takes minutes through the axon tunnel.
    Multi-process safe (local device + shared seed ⇒ identical host trees)."""
    from jax.sharding import PartitionSpec as P

    mesh1 = Mesh(
        np.asarray(jax.local_devices()[:1]).reshape(1, 1, 1),
        ("data", "pipe", "model"),
    )
    fn = jax.jit(
        jax.shard_map(
            init_fn,
            mesh=mesh1,
            in_specs=tuple(P() for _ in args),
            out_specs=P(),
            check_vma=False,
        )
    )
    return jax.device_get(fn(*args))


def make_mesh3(
    num_devices: int | None = None,
    pipeline_parallel: int = 1,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('data', 'pipe', 'model') mesh for 3D parallelism
    (DP × PP × TP, ``parallel/three_d.py``). 'model' is the innermost axis —
    the tensor-parallel all-reduces are the most frequent collective, so they
    get the contiguous-neighbor ICI links; pipeline hops are next; the
    data-parallel gradient mean (once per step) crosses the outermost axis."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    n = len(devices)
    inner = pipeline_parallel * model_parallel
    if n % inner:
        raise ValueError(
            f"{n} devices not divisible by pipeline_parallel*model_parallel={inner}"
        )
    arr = np.array(devices).reshape(n // inner, pipeline_parallel, model_parallel)
    return Mesh(arr, axis_names=("data", "pipe", "model"))


def make_mesh(
    num_devices: int | None = None,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('data', 'model') mesh over local (or given) devices.

    ``model_parallel=1`` (the default, and all the reference needs) yields a
    pure data-parallel mesh."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    arr = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names=("data", "model"))


