"""Device-mesh construction.

Replaces the reference's ``tf.train.ClusterSpec`` + ``replica_device_setter``
placement model (``demo2/train.py:18-29``): instead of pinning variables to
parameter-server processes and ops to worker processes, all devices form a
``jax.sharding.Mesh``; parameters are replicated (or sharded) across it and
XLA inserts ICI collectives where shardings demand.

Axis conventions (room for every strategy even though the reference only
exercises DP — SURVEY §2.3):
  * ``data``  — batch (data-parallel) axis
  * ``model`` — tensor-parallel axis (optional second mesh dim)
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(
    num_devices: int | None = None,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('data', 'model') mesh over local (or given) devices.

    ``model_parallel=1`` (the default, and all the reference needs) yields a
    pure data-parallel mesh."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    arr = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names=("data", "model"))


