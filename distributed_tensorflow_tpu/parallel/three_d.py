"""3D parallelism: DP × PP × TP in one jitted program over a
('data', 'pipe', 'model') mesh.

The composition of the framework's pipeline schedule
(``pipeline_parallel.py`` — GPipe microbatch scan, ``ppermute`` stage hops
over 'pipe') with Megatron tensor parallelism (``tensor_parallel.py`` —
``TpBlock`` with the f/g conjugate collectives over 'model') under the usual
data-parallel batch sharding over 'data'. This is the canonical large-model
recipe: TP inside a stage rides the innermost (fastest) mesh axis, PP hops
cross the middle axis once per tick, and the once-per-step DP gradient mean
crosses the outermost axis.

Composition is clean precisely because of two earlier design choices:
  * the pipeline schedule is block-agnostic — it scans whatever layer apply
    it is given, so a ``TpBlock`` drops in for ``Block``;
  * ``TpBlock`` owns its collectives via custom-VJP pairs (identity-fwd/
    psum-bwd at branch inputs, psum-fwd/identity-bwd at branch outputs), so
    NO model-axis gradient collective is needed no matter what outer
    machinery differentiates through it.

Gradient sync by param group (see the pp/tp modules for derivations):
  stages     — pipe-shard-owned, tp semantics inside      → pmean('data')
  embeddings — live only via stage 0's masked ingest path → psum('pipe'),
               identical across 'model' (_copy_to_tp bwd) → pmean('data')
  ln_f/head  — computed from activations replicated over both 'pipe' and
               'model' with replicated cotangents          → pmean('data')

Verified by exact parity against the 2-axis TP step on the same global
params and batch (which is itself exact against the plain model) —
``tests/test_three_d.py``.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig,
    _attention_fn,
    next_token_loss,
)
from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
    _collect_from_last,
    stack_stage_params,
    unstack_stage_params,
)
from distributed_tensorflow_tpu.parallel.tensor_parallel import (
    TpBlock,
    _spec_for_path,
    init_tp_params,
)
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

__all__ = [
    "init_3d_params",
    "three_d_param_specs",
    "shard_3d_params",
    "build_3d_lm_train_step",
    "stack_stage_params",
    "unstack_stage_params",
]


def init_3d_params(cfg: TransformerConfig, num_stages: int, seed: int = 0) -> Any:
    """GLOBAL-shape host tree: TP-factorized blocks (separate q/k/v, global
    widths) regrouped into pipeline stages — leaves ``(S, L/S, ...)``."""
    return stack_stage_params(init_tp_params(cfg, seed=seed), num_stages)


def three_d_param_specs(tree: Any) -> Any:
    """'stages' leaves: leading stage dim on 'pipe', the layer dim
    replicated, then the TP spec on the param dims (column-parallel kernels
    ``P('pipe', None, None, 'model')``, row-parallel
    ``P('pipe', None, 'model', None)``); everything else replicated. Valid
    for optimizer-state trees too (path-suffix match; scalars → P())."""

    def spec(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        names = [p.key for p in path if hasattr(p, "key")]
        if "stages" not in names:
            return P()
        tp = _spec_for_path(path)  # spec for the UNSTACKED param dims
        return P("pipe", None, *tp)

    return jax.tree_util.tree_map_with_path(spec, tree)


def shard_3d_params(tree: Any, mesh: Mesh, specs: Any | None = None) -> Any:
    from distributed_tensorflow_tpu.parallel.data_parallel import place_by_specs

    return place_by_specs(
        tree, mesh, specs if specs is not None else three_d_param_specs(tree)
    )


def build_3d_lm_train_step(
    cfg: TransformerConfig,
    tx,
    mesh: Mesh,
    params_template: Any,
    num_microbatches: int,
    loss_fn: Callable = next_token_loss,
    donate: bool = True,
):
    """step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, metrics)

    ``params`` from :func:`init_3d_params` placed with
    :func:`shard_3d_params`; ``tokens`` (B, T) sharded over 'data'
    (replicated over 'pipe' and 'model'), local B divisible by
    ``num_microbatches``.
    """
    stage_leaf = jax.tree_util.tree_leaves(params_template["stages"])[0]
    if stage_leaf.shape[0] != mesh.shape["pipe"]:
        raise ValueError(
            f"params stacked for {stage_leaf.shape[0]} stages but mesh "
            f"'pipe' axis has {mesh.shape['pipe']} shards"
        )
    p_specs = three_d_param_specs(params_template)
    o_specs = three_d_param_specs(jax.eval_shape(tx.init, params_template))
    block = TpBlock(cfg, tp_axis="model")
    embed_mod = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype)
    pos_mod = nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.compute_dtype)
    ln_f = nn.LayerNorm(dtype=cfg.compute_dtype)
    head = nn.Dense(
        cfg.vocab_size, dtype=cfg.compute_dtype,
        use_bias=cfg.use_bias,
    )
    attend = _attention_fn(cfg)
    M = num_microbatches

    def forward(params, tokens, rng_drop):
        S = lax.axis_size("pipe")
        stage = lax.axis_index("pipe")
        # Per-stage dropout decorrelation; model shards share the stream
        # (TpBlock dropout sites are replicated activations).
        rng_drop = jax.random.fold_in(rng_drop, stage)
        b, t = tokens.shape
        if b % M:
            raise ValueError(f"local batch {b} not divisible into {M} microbatches")
        bm = b // M

        x = embed_mod.apply({"params": params["tok_embed"]}, tokens)
        rope = getattr(cfg, "position", "learned") == "rope"
        if not rope:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
            x = x + pos_mod.apply({"params": params["pos_embed"]}, positions)
        micro = x.reshape(M, bm, t, cfg.d_model)
        # Under RoPE every microbatch spans the full sequence: TpBlock's
        # positions default (arange(t)) is exactly right, nothing threads
        # through the schedule.

        my_stage = jax.tree_util.tree_map(
            lambda v: jnp.squeeze(v, 0), params["stages"]
        )  # (L/S, ...) local layers, tp-local widths
        n_local_layers = jax.tree_util.tree_leaves(my_stage)[0].shape[0]

        def apply_one(h, layer_params, layer_key):
            return block.apply(
                {"params": layer_params}, h, attend, cfg.dropout_rate > 0,
                rngs={"dropout": layer_key} if cfg.dropout_rate else None,
            )

        if cfg.remat:
            apply_one = jax.checkpoint(apply_one)

        def apply_stage(h, key):
            def layer(h, xs):
                layer_params, i = xs
                return apply_one(h, layer_params, jax.random.fold_in(key, i)), None

            h, _ = lax.scan(layer, h, (my_stage, jnp.arange(n_local_layers)))
            return h

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        n_ticks = M + S - 1

        def tick(carry, ti):
            state, outputs = carry
            # Same drain-tick discard invariant as pipeline_parallel.tick.
            ingest = micro[jnp.minimum(ti, M - 1)]
            inp = jnp.where(stage == 0, ingest, state)
            out = apply_stage(inp, jax.random.fold_in(rng_drop, ti))
            mi = ti - (S - 1)
            write = jnp.logical_and(stage == S - 1, mi >= 0)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, out, outputs[jnp.maximum(mi, 0)]),
                jnp.maximum(mi, 0),
                axis=0,
            )
            state = lax.ppermute(out, "pipe", fwd_perm)
            return (state, outputs), None

        init_outputs = jnp.zeros((M, bm, t, cfg.d_model), cfg.compute_dtype)
        (_, outputs), _ = lax.scan(
            tick,
            (jnp.zeros((bm, t, cfg.d_model), cfg.compute_dtype), init_outputs),
            jnp.arange(n_ticks),
        )
        mask = jnp.where(stage == S - 1, 1.0, 0.0).astype(outputs.dtype)
        outputs = _collect_from_last(outputs, mask, "pipe")
        h = outputs.reshape(b, t, cfg.d_model)
        h = ln_f.apply({"params": params["ln_f"]}, h)
        return head.apply({"params": params["lm_head"]}, h).astype(jnp.float32)

    def _shard_step(params, opt_state, global_step, tokens, rng):
        rng = jax.random.fold_in(
            jax.random.fold_in(rng, global_step), lax.axis_index("data")
        )

        def compute_loss(p):
            return loss_fn(forward(p, tokens, rng), tokens)

        loss, grads = jax.value_and_grad(compute_loss)(params)

        def sync(path, g):
            names = [q.key for q in path if hasattr(q, "key")]
            if "tok_embed" in names or "pos_embed" in names:
                g = lax.psum(g, "pipe")
            return lax.pmean(g, "data")

        grads = jax.tree_util.tree_map_with_path(sync, grads)
        loss = lax.pmean(loss, "data")
        grads = fence_grads(grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, new_opt, global_step + 1, {"loss": loss}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(p_specs, o_specs, P(), P("data", None), P()),
        out_specs=(p_specs, o_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


# ---------------------------------------------------------------------------
# Second composite: DP × SP(ring) × TP — the long-context-at-scale shape.
# Sequence sharded over 'pipe' with ring attention streaming K/V shards
# around that axis; attention heads / FFN sharded over 'model' (TpBlocks);
# batch data-parallel. Composes because TpBlock's attention implementation
# is injectable — the ring closure runs on the LOCAL head shard, and the two
# axes' collectives (ppermute over 'pipe', f/g psums over 'model') never
# touch the same dimension.
# ---------------------------------------------------------------------------


def build_sp_tp_lm_train_step(
    cfg: TransformerConfig,
    tx,
    mesh: Mesh,
    params_template: Any,
    donate: bool = True,
):
    """step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, {'loss'})

    ``tokens`` (B, S) with B sharded over 'data' and S over 'pipe'
    (``P('data','pipe')``); params/opt per ``tensor_parallel.tp_param_specs``
    (replicated over 'data' and 'pipe', sharded over 'model').

    A thin composition: ``sequence_parallel.build_lm_train_step`` provides
    ALL the cross-shard target/loss/gradient machinery (ppermute next-token
    shift, global masked mean over ('data','pipe'), pmean recipe); this
    wrapper only swaps in a ring-attention ``TpTransformerLM`` and the
    tensor-parallel param specs (the 'model' axis needs no grad collective
    of its own — tp's custom-VJP pairs).
    """
    from distributed_tensorflow_tpu.parallel import sequence_parallel as sp
    from distributed_tensorflow_tpu.parallel.ring_attention import ring_attention
    from distributed_tensorflow_tpu.parallel.tensor_parallel import (
        TpTransformerLM,
        tp_param_specs,
    )

    # attention_window composes here the same way as plain SP: the ring
    # truncates to the hops the window can reach (ring_attention's windowed
    # path — O(window) communication per device).
    w = getattr(cfg, "attention_window", None)
    ring = lambda q, k, v: ring_attention(
        q, k, v, axis_name="pipe", causal=True, window=w
    )
    model = TpTransformerLM(TransformerConfig(**{**cfg.__dict__, "attention": ring}))
    return sp.build_lm_train_step(
        cfg,
        tx,
        mesh,
        data_axis="data",
        seq_axis="pipe",
        donate=donate,
        model=model,
        param_specs=tp_param_specs(params_template),
        opt_specs=tp_param_specs(jax.eval_shape(tx.init, params_template)),
    )
