"""Cross-replica parameter-consistency checking.

The reference's async-PS design *embraces* benign data races on parameters
(HogWild updates — SURVEY §5.2). Synchronous SPMD has no such races, but
silent divergence (e.g. non-deterministic host preprocessing leaking into
params, or a bad collective) is the analogous failure mode; this module is the
detector for it: a cheap fingerprint of the param pytree compared across
processes/replicas.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any

import jax
import numpy as np

_COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_list_bytes(shapes: str) -> int:
    """Byte size of an HLO result-type string — a single shape
    (``f32[128,64]{1,0}``) or a tuple (``(f32[10], f32[])``); unknown dtype
    tokens (token, opaque) count zero."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(shapes):
        itemsize = _HLO_DTYPE_BYTES.get(dt)
        if itemsize is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * itemsize
    return total


def hlo_collective_bytes(hlo_text: str, ops=_COLLECTIVE_OPS) -> dict[str, int]:
    """Payload bytes per collective kind in an optimized-HLO dump: for every
    collective-op DEFINITION (the same right-before-operand-paren matcher as
    the count guard in ``tests/test_collectives.py``), sum the byte size of
    its result shape(s). Async ``-start`` forms carry an (operands, results)
    pair in their tuple type, so their bytes are halved — one payload, not
    two. Totals are INVARIANT to XLA's combiner (N per-leaf psums and one
    combined tuple all-reduce move the same bytes), which makes bytes a
    stabler cross-version guard than instruction counts."""
    out: dict[str, int] = {}
    for op in ops:
        total = 0
        for m in re.finditer(
            rf"^\s*(?:ROOT )?%?\S+ = (.*?) ({op}(?:-start)?)\(", hlo_text, re.M
        ):
            shapes, tok = m.groups()
            nbytes = _shape_list_bytes(shapes)
            if tok.endswith("-start"):
                nbytes //= 2
            total += nbytes
        out[op] = total
    return out


def tree_bytes(tree: Any) -> int:
    """Total leaf payload bytes of a pytree (shape × itemsize) — the
    expected-bytes side of the collective payload check (e.g. a DP step's
    gradient all-reduce moves exactly ``tree_bytes(params)`` plus its pmean'd
    metric scalars)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(np.shape(leaf))
        dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        n = 1
        for d in shape:
            n *= int(d)
        total += n * dtype.itemsize
    return total


def param_fingerprint(params: Any) -> str:
    """Deterministic content hash of a pytree (leaf paths + exact bytes)."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(params)[0]
    h = hashlib.sha256()
    for path, leaf in leaves_with_paths:
        h.update(jax.tree_util.keystr(path).encode())
        arr = np.asarray(jax.device_get(leaf))
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def check_cross_process_consistency(params: Any, raise_on_mismatch: bool = True) -> bool:
    """Verify all processes hold bitwise-identical parameters.

    Uses a numeric digest (first 8 bytes of the sha256) all-gathered across
    processes. Single-process: trivially consistent."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    digest = np.frombuffer(bytes.fromhex(param_fingerprint(params)[:16]), dtype=np.uint32)
    gathered = multihost_utils.process_allgather(digest)
    ok = bool(np.all(gathered == gathered[0]))
    if not ok and raise_on_mismatch:
        raise RuntimeError(
            f"parameter divergence across processes: digests {gathered.ravel().tolist()}"
        )
    return ok
