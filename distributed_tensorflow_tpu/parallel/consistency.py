"""Cross-replica parameter-consistency checking.

The reference's async-PS design *embraces* benign data races on parameters
(HogWild updates — SURVEY §5.2). Synchronous SPMD has no such races, but
silent divergence (e.g. non-deterministic host preprocessing leaking into
params, or a bad collective) is the analogous failure mode; this module is the
detector for it: a cheap fingerprint of the param pytree compared across
processes/replicas.
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import numpy as np


def param_fingerprint(params: Any) -> str:
    """Deterministic content hash of a pytree (leaf paths + exact bytes)."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(params)[0]
    h = hashlib.sha256()
    for path, leaf in leaves_with_paths:
        h.update(jax.tree_util.keystr(path).encode())
        arr = np.asarray(jax.device_get(leaf))
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def check_cross_process_consistency(params: Any, raise_on_mismatch: bool = True) -> bool:
    """Verify all processes hold bitwise-identical parameters.

    Uses a numeric digest (first 8 bytes of the sha256) all-gathered across
    processes. Single-process: trivially consistent."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    digest = np.frombuffer(bytes.fromhex(param_fingerprint(params)[:16]), dtype=np.uint32)
    gathered = multihost_utils.process_allgather(digest)
    ok = bool(np.all(gathered == gathered[0]))
    if not ok and raise_on_mismatch:
        raise RuntimeError(
            f"parameter divergence across processes: digests {gathered.ravel().tolist()}"
        )
    return ok
