"""Sequence-parallel (long-context) LM training over a 2-axis mesh.

Composes the framework's two parallelism dimensions in one jitted SPMD
program: the batch is sharded over the ``data`` axis (same scheme as
``data_parallel``) and the **sequence** is sharded over the ``model`` axis,
with attention running as a ring over that axis (``ring_attention``). Memory
per device scales as S/P; gradients are psum-reduced over both axes.

Cross-shard details handled here:
  * positions: each shard embeds its **global** positions
  * next-token targets: the first token of shard i+1 is ppermuted left so the
    last position of shard i has its target; the final global position is
    masked out of the loss
  * loss: global masked mean via psum over both axes
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models.transformer import TransformerConfig, TransformerLM
from distributed_tensorflow_tpu.parallel.ring_attention import ring_attention
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

Batch = dict[str, jnp.ndarray]


def make_sp_model(cfg: TransformerConfig, seq_axis: str = "model") -> TransformerLM:
    """The sequence-parallel variant of a TransformerLM config: same params,
    attention replaced by a causal ring over ``seq_axis``. Param trees are
    interchangeable with the single-device model (attention has no state).

    ``cfg.attention_window`` composes: the ring truncates to the hops the
    window can reach (O(window) communication+compute per device instead of
    O(S) — ``ring_attention``'s windowed path), which is exactly the
    combination a long-context multi-chip run wants."""
    w = getattr(cfg, "attention_window", None)
    ring = lambda q, k, v: ring_attention(
        q, k, v, axis_name=seq_axis, causal=True, window=w
    )
    return TransformerLM(
        TransformerConfig(**{**cfg.__dict__, "attention": ring})
    )


def shard_lm_batch(tokens, mesh: Mesh, data_axis: str = "data", seq_axis: str = "model"):
    """Place (B, S) tokens with batch on the data axis, sequence on the seq axis."""
    return jax.device_put(tokens, NamedSharding(mesh, P(data_axis, seq_axis)))


def build_lm_train_step(
    cfg: TransformerConfig,
    tx: Any,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "model",
    donate: bool = True,
    model: Any | None = None,
    param_specs: Any = None,
    opt_specs: Any = None,
) -> Callable:
    """Jitted SPMD step over sharded tokens (B on data axis, S on seq axis).

    step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, metrics)

    ``model``/``param_specs``/``opt_specs`` generalize the builder beyond
    the replicated-param TransformerLM: ``three_d.build_sp_tp_lm_train_step``
    passes a ring-attention ``TpTransformerLM`` with tensor-parallel specs —
    the cross-shard target/loss/gradient machinery here is identical for
    both (the 'model'/tp axis needs no grad collective of its own)."""
    model = model if model is not None else make_sp_model(cfg, seq_axis)
    param_specs = param_specs if param_specs is not None else P()
    opt_specs = opt_specs if opt_specs is not None else P()
    both_axes = (data_axis, seq_axis)

    def _shard_step(params, opt_state, global_step, tokens, rng):
        seq_idx = lax.axis_index(seq_axis)
        seq_size = lax.psum(1, seq_axis)
        b, s_loc = tokens.shape
        positions = seq_idx * s_loc + jnp.broadcast_to(
            jnp.arange(s_loc, dtype=jnp.int32), (b, s_loc)
        )
        shard_id = lax.axis_index(data_axis) * seq_size + seq_idx
        rng = jax.random.fold_in(jax.random.fold_in(rng, global_step), shard_id)

        # Next-token targets across the shard boundary: receive the first
        # column of the right-neighbor shard (i -> i-1 ppermute).
        perm = [(i, (i - 1) % seq_size) for i in range(seq_size)]

        def compute_loss(p):
            logits = model.apply(
                {"params": p}, tokens, positions=positions, train=True,
                rngs={"dropout": rng} if cfg.dropout_rate else None,
            )
            incoming = lax.ppermute(tokens[:, :1], seq_axis, perm)
            targets = jnp.concatenate([tokens[:, 1:], incoming], axis=1)
            lp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
            # Mask the final global position (its "target" wrapped around).
            is_last = (seq_idx == seq_size - 1) & (
                jnp.arange(s_loc) == s_loc - 1
            )
            w = jnp.where(jnp.broadcast_to(is_last, (b, s_loc)), 0.0, 1.0)
            local_sum = (nll * w).sum()
            local_cnt = w.sum()
            total = lax.psum(local_sum, both_axes)
            count = lax.psum(local_cnt, both_axes)
            return total / jnp.maximum(count, 1.0)

        loss, grads = jax.value_and_grad(compute_loss)(params)
        # With check_vma=False, transposing the in-loss psum broadcasts the
        # cotangent to every shard, so each shard's grad already totals the
        # full global-mean gradient (verified against an unsharded step in
        # test_sp_step_matches_single_device_step). pmean averages the
        # near-identical copies — correct value, bitwise-consistent params.
        grads = lax.pmean(grads, both_axes)
        grads = fence_grads(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        # loss is already a global mean (psum'd inside), identical on all shards.
        return params, opt_state, global_step + 1, {"loss": loss}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(param_specs, opt_specs, P(), P(data_axis, seq_axis), P()),
        out_specs=(param_specs, opt_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)
