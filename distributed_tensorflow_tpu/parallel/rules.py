"""Declarative sharding: regex partition rules over parameter path names.

Every parallel strategy in this package used to hand-wire its
``PartitionSpec``s per model (``tensor_parallel._spec_for_path`` was the
canonical example). This module replaces that with RULE TABLES: an ordered
sequence of ``(regex, PartitionSpec)`` pairs resolved against each
parameter's '/'-joined path — first ``re.search`` hit wins, scalar leaves
are always replicated, and a non-scalar leaf no rule matches is a loud
error (a silent replicate-by-default would hide an exploding-memory bug on
real meshes). Any new model then gets any mesh layout from a table instead
of new code; the serving engine (``serve/engine.ShardedSlotEngine``) is the
first consumer, the TP train path (``tensor_parallel.tp_param_specs``) is
re-expressed on the same primitive, and FSDP-sharded weights can follow by
adding a table.

Two tables ship today, both over the ('data', 'model') mesh of
``parallel/mesh.make_mesh``:

* :data:`TP_TRAIN_RULES` — the Megatron split for ``TpTransformerLM``'s
  SEPARATE q/k/v projections (column-parallel q/k/v/mlp_in with sharded
  bias, row-parallel proj/mlp_out kernels, everything else replicated).
  Exactly reproduces the retired ``_spec_for_path``; pinned by
  ``tests/test_tensor_parallel.py::test_param_specs_rules``.

* :data:`SERVE_TP_RULES` — the same split for the serving
  ``TransformerLM``'s FUSED ``qkv`` projection. Under GSPMD jit (unlike
  ``shard_map``) a spec is a PLACEMENT constraint, not a local-compute
  contract, so splitting the fused ``[q | k | v]`` output columns across
  'model' is valid — XLA partitions the matmul on its output dim and
  inserts the collectives the attention einsums need. Row-parallel
  proj/mlp_out contract over the sharded dim (partial products + one
  all-reduce), the Megatron recipe.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "TP_TRAIN_RULES",
    "SERVE_TP_RULES",
    "match_partition_rules",
    "shardings_from_rules",
]


# Megatron TP for separate-projection TpTransformerLM (training). Biases of
# column-parallel layers carry the output shard ``P('model')``; biases of
# row-parallel layers (``proj_bias`` module param, applied AFTER the
# all-reduce) fall through to replicated.
TP_TRAIN_RULES = (
    (r"(?:^|/)(?:q|k|v|mlp_in)/kernel$", P(None, "model")),
    (r"(?:^|/)(?:q|k|v|mlp_in)/[^/]+$", P("model")),
    (r"(?:^|/)(?:proj|mlp_out)/kernel$", P("model", None)),
    (r".*", P()),
)

# Same split for the serving TransformerLM's fused qkv. proj/mlp_out
# biases (row-parallel, added after the reduce) and embeddings / norms /
# lm_head fall through to replicated — the lm_head matmul runs once per
# emitted token on a (slots, d_model) activation, not worth a collective.
#
# Weight-only-quantized leaves (models/quant.py::QuantDense) shard like
# the kernels they replace, with scales riding the SAME axis:
#   * column-parallel (qkv/mlp_in): ``kernel_q`` (in[/2 packed], out)
#     splits the out axis; the per-output-channel int8 ``scale`` (out,)
#     and the int4 ``gscale`` (groups, out) ride the out shard.
#   * row-parallel (proj/mlp_out): ``kernel_q`` splits the input axis —
#     int4 packed pairs and scale groups stay intact on one device
#     because ServeConfig validation pins group_size | dim/tp; ``gscale``
#     (groups, out) rides the group (input) shard. The int8 per-output
#     ``scale`` multiplies AFTER the all-reduce, so it falls through to
#     replicated with the row-parallel biases.
SERVE_TP_RULES = (
    (r"(?:^|/)(?:qkv|mlp_in)/kernel(?:_q)?$", P(None, "model")),
    (r"(?:^|/)(?:qkv|mlp_in)/(?:bias|scale)$", P("model")),
    (r"(?:^|/)(?:qkv|mlp_in)/gscale$", P(None, "model")),
    (r"(?:^|/)(?:proj|mlp_out)/kernel(?:_q)?$", P("model", None)),
    (r"(?:^|/)(?:proj|mlp_out)/gscale$", P("model", None)),
    (r".*", P()),
)


def _path_name(path) -> str:
    # Mirror tensor_parallel's path naming: only mapping keys contribute
    # (DictKey has .key; GetAttrKey/SequenceKey from optimizer-state
    # containers are structural, not name segments).
    return "/".join(str(p.key) for p in path if hasattr(p, "key"))


def match_partition_rules(rules, params):
    """Resolve a ``PartitionSpec`` pytree for ``params`` from ``rules``.

    ``rules`` is an ordered iterable of ``(regex, PartitionSpec)``; each
    leaf's '/'-joined path is matched with ``re.search`` and the FIRST hit
    wins (order encodes precedence — put the specific rules first and a
    ``('.*', P())`` fallback last if replication is an acceptable
    default). Scalar (0-d) leaves are always replicated regardless of the
    table. A non-scalar leaf that no rule matches raises ``ValueError``.
    """
    rules = tuple(rules)

    def resolve(path, leaf):
        name = _path_name(path)
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"Partition rule not found for param: {name}")

    return jax.tree_util.tree_map_with_path(resolve, params)


def shardings_from_rules(rules, params, mesh):
    """Rule table → per-leaf ``NamedSharding`` pytree for ``mesh`` — the
    form ``jax.jit(in_shardings=...)`` and ``jax.device_put`` take."""
    specs = match_partition_rules(rules, params)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
