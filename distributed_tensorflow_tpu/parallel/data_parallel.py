"""Synchronous SPMD data-parallel training over a device mesh.

This is the TPU-native replacement for the reference's **asynchronous
parameter-server** data parallelism (``demo2/train.py:18-29,149,166-193``):
workers there pull stale variables from ps hosts over gRPC, compute gradients
locally, and push un-synchronized updates back (HogWild). On TPU the idiomatic
equivalent is synchronous SPMD: the batch is sharded over the mesh's ``data``
axis, every device computes gradients on its shard, and a single
``lax.psum``-mean over ICI replaces the two gRPC crossings per step.
Documented divergence (SURVEY §2.2): sync DP ≥ async PS in convergence per
step; async PS semantics are an anti-pattern on TPU.

Implementation: ``jax.shard_map`` with explicit collectives (not relying on
sharding propagation) so the communication pattern is visible and auditable;
the whole step (fwd + bwd + psum + optimizer) is one jitted XLA program —
parameters never leave HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops.losses import (
    accuracy,
    correct_mask,
    per_example_cross_entropy,
    softmax_cross_entropy,
)

Batch = dict[str, jnp.ndarray]


def fence_grads(grads: Any) -> Any:
    """``lax.optimization_barrier`` between the gradient tree and the
    optimizer update — identity on values, but XLA may not fuse across it.

    Without the fence XLA folds the Adam elementwise chain into the
    weight-gradient matmuls' epilogues, and the fused dW ops run measurably
    over the matmul roofline: the r4 XPlane budget attributed ~16 ms/step
    of epilogue overhead at the flagship LM shape, and fencing recovered
    10-12 ms/step — **72.6% → 74.7% MFU**, reproduced in reversed A/B order
    (tools/adam_fusion_probe.py, r5). Applied by every train-step builder
    right before ``tx.update``; numerics and collective structure are
    untouched (the barrier is not a collective)."""
    return lax.optimization_barrier(grads)


def _to_global(tree: Any, sharding: NamedSharding) -> Any:
    """Place host data onto a (possibly multi-process) sharding. Single
    process: plain device_put. Multi-process: every process contributes the
    block for its own devices via ``make_array_from_process_local_data`` —
    ``device_put`` cannot address other hosts' devices."""
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)), tree
    )


def place_by_specs(tree: Any, mesh: Mesh, specs: Any) -> Any:
    """Place a host tree leaf-by-leaf per a matching PartitionSpec tree.
    Every process passes the same full GLOBAL values; the multi-process path
    uses ``make_array_from_callback`` (each process serves exactly its
    addressable shards' slices — correct even when a sharded axis spans
    processes). Used by the TP and PP param placements."""

    def place(x, s):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, s)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])

    return jax.tree_util.tree_map(place, tree, specs)


@jax.jit
def _copy_leaves(leaves):
    return [jnp.copy(x) for x in leaves]


def device_copy(leaves: list) -> list:
    """Fresh on-device buffers for a list of ``jax.Array`` leaves — the
    checkpoint snapshot stage's defensive copy. The copies are owned by the
    snapshot alone, so a later train dispatch that DONATES the originals
    (every MNIST-path step builder donates by default) can never invalidate
    what the background device→host fetch reads. One asynchronous dispatch;
    the cost is one transient extra copy of the tree in device memory — the
    device half of the snapshot double buffer. Sharded inputs keep their
    shardings (the copy is collective-free), so every process must call this
    at the same program point in multi-process runs, like any jit."""
    return _copy_leaves(leaves)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree fully-replicated over the mesh (params/opt state live in
    HBM once per device — the reference instead kept one copy on ps hosts and
    shipped it over the network every step). Multi-process: every process must
    pass the same host values (chief-seeded init or a restored checkpoint).

    Caveat: when a leaf is already a device array with a compatible sharding,
    ``device_put`` may return it as-is (no copy). Donating the result to a
    train step then invalidates the caller's original array. Keep initial
    params host-side (numpy) if you need them after training starts."""
    return _to_global(tree, NamedSharding(mesh, P()))


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Split dim 0 of every array over the 'data' axis.

    Multi-process: ``batch`` is this process's LOCAL portion (global dim 0 =
    local dim 0 × process_count) — each worker feeds its own independently
    sampled examples, the SPMD analog of the reference's per-worker
    independent shuffles (``demo2/train.py:182``). For identical-on-all-hosts
    data (eval sweeps) use :func:`shard_global_batch`."""
    sharding = NamedSharding(mesh, P(("data", "model")))
    return _to_global(batch, sharding)


def shard_global_batch(batch: Batch, mesh: Mesh, spec: P | None = None) -> Batch:
    """Shard a batch that every process holds IDENTICALLY (deterministic eval
    chunks / step-keyed LM batches): the global array equals the logical
    batch exactly once, each process contributing its own devices' slices.
    ``spec`` defaults to the 2-axis batch sharding; pass e.g.
    ``P('data', 'pipe')`` on a ('data','pipe','model') mesh.

    Multi-process placement goes through ``make_array_from_callback`` (each
    process serves exactly its addressable shards' index slices of the full
    global value) — correct for ANY spec, including ones where the leading
    batch axis does NOT span the processes (a batch-dim slice-by-process
    would hand devices garbage there)."""
    resolved = spec if spec is not None else P(("data", "model"))
    if jax.process_count() == 1:
        return _to_global(batch, NamedSharding(mesh, resolved))
    return place_by_specs(
        batch, mesh, jax.tree_util.tree_map(lambda _: resolved, batch)
    )


def _global_grad_norm(grads: Any) -> jnp.ndarray:
    """Global L2 norm of a gradient tree, accumulated in f32 (the same
    quantity optax's clip_by_global_norm gates on)."""
    leaves = jax.tree_util.tree_leaves(grads)
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(total)


def guarded_apply(tx, params, opt_state, grads):
    """Non-finite-step guard: apply the optimizer update only when the global
    gradient norm is finite; otherwise keep params AND opt state untouched
    (a NaN step must not advance Adam's moments either — one poisoned moment
    buffer corrupts every later step). Returns
    ``(params, opt_state, skipped)`` with ``skipped`` a 0/1 f32 scalar the
    loops aggregate into the ``skipped_nonfinite`` metric.

    ``lax.cond`` keeps the gate jit/scan-compatible: the predicate is
    replicated across the mesh (grads are post-pmean), so every device takes
    the same branch."""
    finite = jnp.isfinite(_global_grad_norm(grads))

    def _apply(operands):
        p, o, g = operands
        g = fence_grads(g)
        updates, o = tx.update(g, o, p)
        p = jax.tree_util.tree_map(lambda a, u: a + u, p, updates)
        return p, o

    def _skip(operands):
        p, o, _ = operands
        return p, o

    params, opt_state = lax.cond(finite, _apply, _skip, (params, opt_state, grads))
    return params, opt_state, 1.0 - finite.astype(jnp.float32)


def _shard_index(data_axes: tuple[str, str]):
    """Flat per-device index over the (data, model) axes — the one identity
    used by both the dropout stream and the pool-sampling stream."""
    return lax.axis_index(data_axes[0]) * lax.axis_size(data_axes[1]) + lax.axis_index(
        data_axes[1]
    )


def _grad_and_metrics(apply_fn: Callable, loss_fn: Callable, params, batch, rng):
    """One forward+backward on a local batch shard: the single source of
    truth for the train-step loss body (plain, fused, pool and accumulation
    paths all call this)."""

    def compute_loss(p):
        logits = apply_fn(
            {"params": p}, batch["image"], train=True, rngs={"dropout": rng}
        )
        return loss_fn(logits, batch["label"]), logits

    (loss, logits), grads = jax.value_and_grad(compute_loss, has_aux=True)(params)
    return grads, loss, accuracy(logits, batch["label"])


def _make_shard_step(
    apply_fn: Callable,
    tx,
    loss_fn: Callable,
    data_axes: tuple[str, str] = ("data", "model"),
    guard_nonfinite: bool = True,
):
    """The per-step SPMD body shared by :func:`build_train_step` (one step per
    dispatch) and :func:`build_multi_step` (k steps per dispatch)."""

    def _shard_step(params, opt_state, global_step, batch, rng):
        # Distinct dropout noise per step (fold in the on-device global step —
        # no per-step host-side key derivation/dispatch) and per shard.
        shard_id = _shard_index(data_axes)
        rng = jax.random.fold_in(jax.random.fold_in(rng, global_step), shard_id)
        grads, loss, acc = _grad_and_metrics(apply_fn, loss_fn, params, batch, rng)
        # THE collective: gradient mean over ICI (replaces worker->ps gRPC push).
        grads = lax.pmean(grads, data_axes)
        loss = lax.pmean(loss, data_axes)
        acc = lax.pmean(acc, data_axes)
        metrics = {"loss": loss, "accuracy": acc}
        if guard_nonfinite:
            params, opt_state, skipped = guarded_apply(tx, params, opt_state, grads)
            metrics["skipped_nonfinite"] = skipped
        else:
            grads = fence_grads(grads)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        # global_step advances either way — a skipped update must not shift
        # the data/RNG alignment of every later step.
        return params, opt_state, global_step + 1, metrics

    return _shard_step


def build_train_step(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
    guard_nonfinite: bool = True,
):
    """Build a jitted SPMD train step.

    step(params, opt_state, global_step, batch, rng)
        -> (params, opt_state, global_step, metrics)

    ``global_step`` is the reference's chief-maintained global step
    (``demo2/train.py:146-149``) — here every device holds the same
    replicated counter, incremented exactly once per synchronous step.
    With ``guard_nonfinite`` (default) a non-finite global grad norm skips
    the update (see :func:`guarded_apply`) and metrics carry a 0/1
    ``skipped_nonfinite`` scalar.
    """
    shard_fn = jax.shard_map(
        _make_shard_step(apply_fn, tx, loss_fn, guard_nonfinite=guard_nonfinite),
        mesh=mesh,
        in_specs=(P(), P(), P(), P(("data", "model")), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_multi_step(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
    guard_nonfinite: bool = True,
):
    """k fused train steps per dispatch: ``lax.scan`` over a stacked batch.

    multi_step(params, opt_state, global_step, batches, rng)
        -> (params, opt_state, global_step, metrics)   # metrics stacked (k,)

    ``batches`` arrays carry a leading steps dim: ``image (k, B, ...)``. One
    XLA program runs k optimizer steps back-to-back on device, so the
    per-dispatch Python/runtime overhead — what dominates small-model steps
    like the reference's MNIST convnet — is paid once per k steps instead of
    every step. Semantics are identical to k calls of :func:`build_train_step`
    (same per-step RNG folding via the carried global_step).
    """
    step = _make_shard_step(apply_fn, tx, loss_fn, guard_nonfinite=guard_nonfinite)

    def _shard_multi(params, opt_state, global_step, batches, rng):
        def body(carry, batch):
            p, o, g = carry
            p, o, g, metrics = step(p, o, g, batch, rng)
            return (p, o, g), metrics

        (params, opt_state, global_step), metrics = lax.scan(
            body, (params, opt_state, global_step), batches
        )
        return params, opt_state, global_step, metrics

    shard_fn = jax.shard_map(
        _shard_multi,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(None, ("data", "model")), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_accum_train_step(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
    guard_nonfinite: bool = True,
):
    """Gradient accumulation: ONE optimizer step from k microbatch gradient
    means — the way to train at an effective batch size whose activations
    don't fit HBM (each microbatch's activations are freed before the next;
    only the gradient accumulator persists).

    accum_step(params, opt_state, global_step, batches, rng)
        -> (params, opt_state, global_step, metrics)

    ``batches`` arrays carry a leading microbatch dim: ``image
    (k, B_micro, ...)`` (shard with :func:`stack_shard_batches`); k is taken
    from that dim, so the same compiled step serves any microbatch count of
    the same shape. With equal microbatch sizes, the mean-of-means equals
    the full-batch gradient mean, so semantics match one
    :func:`build_train_step` call on the concatenated batch (exact up to
    float summation order). Unlike :func:`build_multi_step` — k *optimizer*
    steps per dispatch — this runs k *gradient* passes and one update;
    ``global_step`` advances by 1. Dropout noise is folded per microbatch
    (distinct masks, as k separate forward passes would get).
    """
    data_axes = ("data", "model")

    def _shard_accum(params, opt_state, global_step, batches, rng):
        k = jax.tree_util.tree_leaves(batches)[0].shape[0]
        shard_id = _shard_index(data_axes)
        base = jax.random.fold_in(jax.random.fold_in(rng, global_step), shard_id)

        def body(carry, inp):
            acc, i = carry
            grads, loss, acc_metric = _grad_and_metrics(
                apply_fn, loss_fn, params, inp, jax.random.fold_in(base, i)
            )
            acc = jax.tree_util.tree_map(lambda a, g_: a + g_, acc, grads)
            return (acc, i + 1), (loss, acc_metric)

        zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        (grad_sum, _), (losses, accs) = lax.scan(
            body, (zero, jnp.zeros((), jnp.int32)), batches
        )
        grads = jax.tree_util.tree_map(lambda g_: g_ / k, grad_sum)
        grads = lax.pmean(grads, data_axes)
        loss = lax.pmean(jnp.mean(losses), data_axes)
        acc = lax.pmean(jnp.mean(accs), data_axes)
        metrics = {"loss": loss, "accuracy": acc}
        if guard_nonfinite:
            params, opt_state, skipped = guarded_apply(tx, params, opt_state, grads)
            metrics["skipped_nonfinite"] = skipped
        else:
            grads = fence_grads(grads)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, global_step + 1, metrics

    shard_fn = jax.shard_map(
        _shard_accum,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(None, ("data", "model")), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_pool_train_fn(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    batch_per_shard: int,
    steps_per_call: int,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
    guard_nonfinite: bool = True,
):
    """Device-resident-dataset training: k steps per dispatch, batches
    gathered on device from an HBM-resident example pool.

    pool_fn(params, opt_state, global_step, pool, rng)
        -> (params, opt_state, global_step, metrics)   # metrics stacked (k,)

    ``pool`` is the full (sharded) training set placed once with
    :func:`shard_batch`; each device samples ``batch_per_shard`` examples per
    step from its local shard (uniform with replacement, keyed on the carried
    global step). The hot loop involves the host ONLY to dispatch — no batch
    assembly, no HBM transfer. This is the logical endpoint of the prefetch
    story: the reference re-uploaded every batch via feed_dict
    (``demo1/train.py:153-155``); per-shard independent sampling mirrors the
    reference's per-worker independent shuffles (``demo2/train.py:182``).
    """
    data_axes = ("data", "model")
    step = _make_shard_step(apply_fn, tx, loss_fn, data_axes, guard_nonfinite=guard_nonfinite)

    def _shard_pool_train(params, opt_state, global_step, pool, rng):
        n_local = pool["image"].shape[0]
        shard_id = _shard_index(data_axes)

        def body(carry, _):
            p, o, g = carry
            # Separate index stream from the dropout stream (extra fold tag).
            idx_key = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(rng, 0x5A11), g), shard_id
            )
            idx = jax.random.randint(idx_key, (batch_per_shard,), 0, n_local)
            batch = {k: jnp.take(v, idx, axis=0) for k, v in pool.items()}
            p, o, g, metrics = step(p, o, g, batch, rng)
            return (p, o, g), metrics

        (params, opt_state, global_step), metrics = lax.scan(
            body, (params, opt_state, global_step), None, length=steps_per_call
        )
        return params, opt_state, global_step, metrics

    shard_fn = jax.shard_map(
        _shard_pool_train,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(("data", "model")), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def shard_pool(images, labels, mesh: Mesh) -> Batch:
    """Place a whole training set in HBM for :func:`build_pool_train_fn`,
    truncated to a multiple of the mesh size (shards must be even; the loss
    is <mesh_size examples). Multi-process: every process holds the same full
    dataset on the host (each downloads/loads its own copy, as the reference's
    workers did) and contributes its devices' slice."""
    n = np.asarray(images).shape[0]
    n -= n % mesh.devices.size
    return shard_global_batch(
        {"image": np.asarray(images)[:n], "label": np.asarray(labels)[:n]}, mesh
    )


def stack_shard_batches(batches: list[Batch], mesh: Mesh) -> Batch:
    """Stack k host batches into one ``(k, B, ...)`` pytree sharded for
    :func:`build_multi_step` (steps dim replicated, batch dim sharded).
    Multi-process: like :func:`shard_batch`, each process passes its LOCAL
    k batches (global batch dim = local × process_count)."""
    stacked = {
        k: np.stack([np.asarray(b[k]) for b in batches]) for k in batches[0]
    }
    return _to_global(stacked, NamedSharding(mesh, P(None, ("data", "model"))))


def build_lm_train_step(cfg, tx, mesh: Mesh, donate: bool = False):
    """Data-parallel LM train step: tokens ``(B, S)`` sharded over the mesh,
    replicated params, ``lax.pmean`` gradient sync — the LM counterpart of
    :func:`build_train_step`, shared by ``tools/train_lm.py`` (``dp`` mode)
    and the bench harness.

    step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, {"loss"})
    """
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerLM,
        next_token_loss,
    )

    model = TransformerLM(cfg)

    def _shard_step(p, o, g, tokens, key):
        del key  # no dropout in the LM pretraining path

        def compute(pp_):
            logits = model.apply({"params": pp_}, tokens)
            return next_token_loss(logits, tokens)

        loss, grads = jax.value_and_grad(compute)(p)
        grads = lax.pmean(grads, ("data", "model"))
        loss = lax.pmean(loss, ("data", "model"))
        grads = fence_grads(grads)
        updates, o = tx.update(grads, o, p)
        p = jax.tree_util.tree_map(lambda a, u: a + u, p, updates)
        return p, o, g + 1, {"loss": loss}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(("data", "model"), None), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_lm_multi_step(cfg, tx, mesh: Mesh, donate: bool = False):
    """k fused LM train steps per dispatch: ``lax.scan`` over stacked tokens
    ``(k, B, S)`` (steps dim replicated, batch dim sharded) — the LM
    counterpart of :func:`build_multi_step`, used by ``tools/train_lm.py
    --steps_per_call``. Semantics identical to k calls of
    :func:`build_lm_train_step`; returns stacked ``(k,)`` losses."""
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerLM,
        next_token_loss,
    )

    model = TransformerLM(cfg)

    def _shard_multi(p, o, g, tokens_k, key):
        del key  # no dropout in the LM pretraining path

        def body(carry, tokens):
            p_, o_, g_ = carry

            def compute(pp_):
                logits = model.apply({"params": pp_}, tokens)
                return next_token_loss(logits, tokens)

            loss, grads = jax.value_and_grad(compute)(p_)
            grads = lax.pmean(grads, ("data", "model"))
            loss = lax.pmean(loss, ("data", "model"))
            grads = fence_grads(grads)
            updates, o_ = tx.update(grads, o_, p_)
            p_ = jax.tree_util.tree_map(lambda a, u: a + u, p_, updates)
            return (p_, o_, g_ + 1), loss

        (p, o, g), losses = lax.scan(body, (p, o, g), tokens_k)
        return p, o, g, {"loss": losses}

    shard_fn = jax.shard_map(
        _shard_multi,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(None, ("data", "model"), None), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_eval_step(apply_fn: Callable, mesh: Mesh):
    """Jitted SPMD eval step: returns summed correct-count and summed
    per-example cross-entropy over the global (sharded) batch so the host can
    aggregate exact full-dataset accuracy across uneven batch loops."""

    def _shard_eval(params, batch):
        logits = apply_fn({"params": params}, batch["image"], train=False)
        # ``weight`` masks padding rows (see ``pad_to_multiple``).
        w = batch.get("weight", jnp.ones((batch["image"].shape[0],), jnp.float32))
        correct = lax.psum(jnp.sum(correct_mask(logits, batch["label"]) * w), ("data", "model"))
        loss_sum = lax.psum(
            jnp.sum(per_example_cross_entropy(logits, batch["label"]) * w), ("data", "model")
        )
        return correct, loss_sum

    shard_fn = jax.shard_map(
        _shard_eval,
        mesh=mesh,
        in_specs=(P(), P(("data", "model"))),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def build_apply_fn(apply_fn: Callable, mesh: Mesh):
    """Jitted sharded inference: logits for a (possibly large) batch."""

    def _shard_apply(params, images):
        return apply_fn({"params": params}, images, train=False)

    shard_fn = jax.shard_map(
        _shard_apply,
        mesh=mesh,
        in_specs=(P(), P(("data", "model"))),
        out_specs=P(("data", "model")),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def pad_to_multiple(batch: Batch, multiple: int) -> tuple[Batch, int]:
    """Pad dim 0 up to a multiple of the mesh size (XLA needs static, evenly
    divisible shard shapes) and attach a ``weight`` mask (1=real, 0=padding).
    Returns (padded batch, original size)."""
    n = next(iter(batch.values())).shape[0]
    rem = (-n) % multiple
    weight = np.concatenate([np.ones(n, np.float32), np.zeros(rem, np.float32)])
    padded = {
        k: np.concatenate([np.asarray(v), np.zeros((rem,) + v.shape[1:], v.dtype)])
        if rem
        else np.asarray(v)
        for k, v in batch.items()
    }
    padded["weight"] = weight
    return padded, n
