"""Synchronous SPMD data-parallel training over a device mesh.

This is the TPU-native replacement for the reference's **asynchronous
parameter-server** data parallelism (``demo2/train.py:18-29,149,166-193``):
workers there pull stale variables from ps hosts over gRPC, compute gradients
locally, and push un-synchronized updates back (HogWild). On TPU the idiomatic
equivalent is synchronous SPMD: the batch is sharded over the mesh's ``data``
axis, every device computes gradients on its shard, and a single
``lax.psum``-mean over ICI replaces the two gRPC crossings per step.
Documented divergence (SURVEY §2.2): sync DP ≥ async PS in convergence per
step; async PS semantics are an anti-pattern on TPU.

Implementation: ``jax.shard_map`` with explicit collectives (not relying on
sharding propagation) so the communication pattern is visible and auditable;
the whole step (fwd + bwd + psum + optimizer) is one jitted XLA program —
parameters never leave HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops.losses import (
    accuracy,
    correct_mask,
    per_example_cross_entropy,
    softmax_cross_entropy,
)

Batch = dict[str, jnp.ndarray]


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree fully-replicated over the mesh (params/opt state live in
    HBM once per device — the reference instead kept one copy on ps hosts and
    shipped it over the network every step)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Split dim 0 of every array over the 'data' axis."""
    sharding = NamedSharding(mesh, P(("data", "model")))
    return jax.device_put(batch, sharding)


def build_train_step(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
):
    """Build a jitted SPMD train step.

    step(params, opt_state, global_step, batch, rng)
        -> (params, opt_state, global_step, metrics)

    ``global_step`` is the reference's chief-maintained global step
    (``demo2/train.py:146-149``) — here every device holds the same
    replicated counter, incremented exactly once per synchronous step.
    """
    data_axes = ("data", "model")  # batch sharded over both axes when model dim >1

    def _shard_step(params, opt_state, global_step, batch, rng):
        # Distinct dropout noise per step (fold in the on-device global step —
        # no per-step host-side key derivation/dispatch) and per shard.
        shard_id = lax.axis_index(data_axes[0]) * lax.axis_size(data_axes[1]) + lax.axis_index(
            data_axes[1]
        )
        rng = jax.random.fold_in(jax.random.fold_in(rng, global_step), shard_id)

        def compute_loss(p):
            logits = apply_fn(
                {"params": p}, batch["image"], train=True, rngs={"dropout": rng}
            )
            return loss_fn(logits, batch["label"]), logits

        (loss, logits), grads = jax.value_and_grad(compute_loss, has_aux=True)(params)
        # THE collective: gradient mean over ICI (replaces worker->ps gRPC push).
        grads = lax.pmean(grads, data_axes)
        loss = lax.pmean(loss, data_axes)
        acc = lax.pmean(accuracy(logits, batch["label"]), data_axes)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, global_step + 1, {"loss": loss, "accuracy": acc}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(("data", "model")), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_eval_step(apply_fn: Callable, mesh: Mesh):
    """Jitted SPMD eval step: returns summed correct-count and summed
    per-example cross-entropy over the global (sharded) batch so the host can
    aggregate exact full-dataset accuracy across uneven batch loops."""

    def _shard_eval(params, batch):
        logits = apply_fn({"params": params}, batch["image"], train=False)
        # ``weight`` masks padding rows (see ``pad_to_multiple``).
        w = batch.get("weight", jnp.ones((batch["image"].shape[0],), jnp.float32))
        correct = lax.psum(jnp.sum(correct_mask(logits, batch["label"]) * w), ("data", "model"))
        loss_sum = lax.psum(
            jnp.sum(per_example_cross_entropy(logits, batch["label"]) * w), ("data", "model")
        )
        return correct, loss_sum

    shard_fn = jax.shard_map(
        _shard_eval,
        mesh=mesh,
        in_specs=(P(), P(("data", "model"))),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def build_apply_fn(apply_fn: Callable, mesh: Mesh):
    """Jitted sharded inference: logits for a (possibly large) batch."""

    def _shard_apply(params, images):
        return apply_fn({"params": params}, images, train=False)

    shard_fn = jax.shard_map(
        _shard_apply,
        mesh=mesh,
        in_specs=(P(), P(("data", "model"))),
        out_specs=P(("data", "model")),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def pad_to_multiple(batch: Batch, multiple: int) -> tuple[Batch, int]:
    """Pad dim 0 up to a multiple of the mesh size (XLA needs static, evenly
    divisible shard shapes) and attach a ``weight`` mask (1=real, 0=padding).
    Returns (padded batch, original size)."""
    import numpy as np

    n = next(iter(batch.values())).shape[0]
    rem = (-n) % multiple
    weight = np.concatenate([np.ones(n, np.float32), np.zeros(rem, np.float32)])
    padded = {
        k: np.concatenate([np.asarray(v), np.zeros((rem,) + v.shape[1:], v.dtype)])
        if rem
        else np.asarray(v)
        for k, v in batch.items()
    }
    padded["weight"] = weight
    return padded, n
