"""Expert parallelism (switch-style MoE) over the mesh's ``model`` axis.

Completes the framework's parallelism coverage (DP / TP / SP / PP / EP — the
reference has only async DP, SURVEY §2.3). Design:

  * :class:`MoeMlp` replaces a transformer block's dense MLP with E experts
    and a top-1 router (Switch Transformer): per token, the router picks one
    expert; tokens are dispatched into per-expert capacity buffers with
    deterministic position-priority truncation (capacity
    ``ceil(tokens/E · capacity_factor)``);
  * experts are SHARDED over 'model': each shard owns E/P experts (stacked
    leading dim). Dispatch/combine run on every shard's local tokens; a pair
    of ``lax.all_to_all`` collectives exchanges the capacity buffers so each
    expert processes the tokens routed to it from every shard — compute
    travels to the expert's owner, tokens come back combined;
  * the router adds the standard load-balance auxiliary loss
    (E · Σ_e fraction_e · mean_prob_e).

Numerics: ep=P equals ep=1 exactly (same experts, same routing, relocation
only) — verified in ``tests/test_expert_parallel.py``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig,
    _attention_fn,
    attention_sublayer,
    next_token_loss,
)
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

__all__ = [
    "MoeMlp",
    "moe_param_specs",
    "shard_moe_params",
    "build_moe_layer_fn",
    "MoeTransformerLM",
    "init_moe_lm_params",
    "build_moe_lm_train_step",
]


def _exchange(x, axis: str):
    """The capacity-buffer exchange as a custom-VJP involution: forward is
    ``all_to_all`` over dim 0 (shard i's chunk j → shard j's slot i — applying
    it twice is the identity), backward is the SAME exchange on the cotangent,
    unscaled. Raw ``lax.all_to_all`` must not be used: its shard_map transpose
    accumulates the replicated cotangent once per shard (measured: exactly
    ×ep gradient inflation on every expert parameter — the same AD pitfall as
    the raw-psum cases in tensor/pipeline parallelism)."""

    @jax.custom_vjp
    def f(v):
        return lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=False)

    def fwd(v):
        return f(v), None

    def bwd(_, t):
        return (lax.all_to_all(t, axis, split_axis=0, concat_axis=0, tiled=False),)

    f.defvjp(fwd, bwd)
    return f(x)


class MoeMlp(nn.Module):
    """Top-1 (switch) mixture-of-experts MLP, expert-parallel over ``ep_axis``.

    Call inside shard_map: input (N, D) local tokens → (output (N, D),
    aux_loss scalar). Experts' params are stacked ``(E, ...)`` globally and
    sharded ``P('model')`` — inside shard_map each shard sees ``(E/P, ...)``.
    """

    cfg: TransformerConfig
    num_experts: int
    capacity_factor: float = 2.0
    ep_axis: str = "model"

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        d = cfg.compute_dtype
        E = self.num_experts
        ep = lax.axis_size(self.ep_axis)
        if E % ep:
            raise ValueError(f"num_experts {E} not divisible by ep={ep}")
        local_e = E // ep
        n, _ = x.shape
        cap = int(np.ceil(n / E * self.capacity_factor))

        # Router (replicated params): top-1 expert per token.
        logits = nn.Dense(E, dtype=d, param_dtype=jnp.float32, name="router")(x)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        expert_idx = jnp.argmax(probs, -1)  # (N,)
        expert_prob = jnp.take_along_axis(probs, expert_idx[:, None], -1)[:, 0]

        # Load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e.
        one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (N, E)
        fraction = one_hot.mean(0)
        mean_prob = probs.mean(0)
        aux = E * jnp.sum(fraction * mean_prob)

        # Capacity assignment: position-priority within each expert.
        pos_in_expert = jnp.cumsum(one_hot, axis=0) * one_hot - 1.0  # (N, E)
        kept = (pos_in_expert < cap) & (one_hot > 0)
        # dispatch: (N, E, C) one-hot; combine adds the router prob weight.
        pos = jnp.clip(pos_in_expert, 0, cap - 1).astype(jnp.int32)
        dispatch = (
            kept[..., None] & (jax.nn.one_hot(pos, cap, dtype=jnp.bool_))
        ).astype(d)
        combine = dispatch.astype(jnp.float32) * expert_prob[:, None, None]

        # To expert buffers: (E, C, D) = tokens grouped by chosen expert.
        buf = jnp.einsum("nd,nec->ecd", x.astype(d), dispatch)
        # Exchange: each shard keeps its local_e experts' buffers from EVERY
        # shard. (E, C, D) -> (ep, local_e, C, D) -> all_to_all over shards
        # -> (ep, local_e, C, D) where dim0 is now the SOURCE shard.
        buf = buf.reshape(ep, local_e, cap, cfg.d_model)
        buf = _exchange(buf, self.ep_axis)
        # (ep, local_e, C, D): tokens for MY experts from all source shards.
        buf = buf.transpose(1, 0, 2, 3).reshape(local_e, ep * cap, cfg.d_model)

        # Apply local experts (stacked params, scanned).
        w_in = self.param(
            "w_in",
            nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
            (local_e, cfg.d_model, cfg.d_ff),
            jnp.float32,
        )
        b_in = self.param("b_in", nn.initializers.zeros, (local_e, cfg.d_ff), jnp.float32)
        w_out = self.param(
            "w_out",
            nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
            (local_e, cfg.d_ff, cfg.d_model),
            jnp.float32,
        )
        b_out = self.param(
            "b_out", nn.initializers.zeros, (local_e, cfg.d_model), jnp.float32
        )

        def expert(tokens, wi, bi, wo, bo):
            h = jnp.einsum("cd,df->cf", tokens, wi.astype(d)) + bi.astype(d)
            h = nn.gelu(h)
            return jnp.einsum("cf,fd->cd", h, wo.astype(d)) + bo.astype(d)

        out = jax.vmap(expert)(buf, w_in, b_in, w_out, b_out)  # (local_e, ep*C, D)

        # Route back: inverse all_to_all, then combine on the source shard.
        out = out.reshape(local_e, ep, cap, cfg.d_model).transpose(1, 0, 2, 3)
        out = _exchange(out, self.ep_axis)
        out = out.reshape(E, cap, cfg.d_model)
        y = jnp.einsum("ecd,nec->nd", out.astype(jnp.float32), combine)
        return y.astype(d), aux


class MoeBlock(nn.Module):
    """Transformer block with the dense MLP replaced by :class:`MoeMlp`.
    Attention is the plain (replicated) path; returns (x, aux_loss)."""

    cfg: TransformerConfig
    num_experts: int
    capacity_factor: float = 2.0
    ep_axis: str = "model"

    @nn.compact
    def __call__(self, x, attend, train: bool = False, positions=None):
        cfg = self.cfg
        d = cfg.compute_dtype
        x, _ = attention_sublayer(
            cfg, x, attend, train=train, positions=positions
        )
        b, s, _unused = x.shape

        h = nn.LayerNorm(dtype=d, name="ln2")(x)
        y, aux = MoeMlp(
            cfg,
            num_experts=self.num_experts,
            capacity_factor=self.capacity_factor,
            ep_axis=self.ep_axis,
            name="moe",
        )(h.reshape(b * s, cfg.d_model))
        y = y.reshape(b, s, cfg.d_model)
        # Dropout sites live on REPLICATED activations (the MoE output is
        # identical on every model shard), so ep parity stays exact.
        if cfg.dropout_rate:
            y = nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)
        return x + y, aux


class MoeTransformerLM(nn.Module):
    """Decoder LM with MoE MLPs in every block (expert-parallel over
    ``ep_axis``). MUST run inside shard_map. Returns (logits, total_aux)."""

    cfg: TransformerConfig
    num_experts: int
    capacity_factor: float = 2.0
    ep_axis: str = "model"

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = False):
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype, name="tok_embed")(
            tokens
        )
        rope = getattr(cfg, "position", "learned") == "rope"
        if not rope:
            x = x + nn.Embed(
                cfg.max_seq_len, cfg.d_model, dtype=cfg.compute_dtype, name="pos_embed"
            )(positions)
        attend = _attention_fn(cfg, prefer_packed=True)
        aux_total = jnp.zeros((), jnp.float32)
        # cfg.remat: recompute each block on backward. The all_to_all token
        # exchange replays identically on every shard (pure function of the
        # saved block input), so recomputation is SPMD-safe.
        block_cls = (
            nn.remat(MoeBlock, static_argnums=(2, 3)) if cfg.remat else MoeBlock
        )
        for i in range(cfg.num_layers):
            x, aux = block_cls(
                cfg,
                num_experts=self.num_experts,
                capacity_factor=self.capacity_factor,
                ep_axis=self.ep_axis,
                name=f"block_{i}",
            )(x, attend, train, positions=positions if rope else None)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, dtype=cfg.compute_dtype, name="lm_head",
            use_bias=cfg.use_bias,
        )(x)
        return logits.astype(jnp.float32), aux_total / cfg.num_layers


def init_moe_lm_params(
    cfg: TransformerConfig, num_experts: int, seed: int = 0, sample_len: int = 8, **kw
) -> Any:
    """GLOBAL-shape host params (1×1 shard_map init, like the MoE layer's)."""
    from distributed_tensorflow_tpu.parallel.mesh import unit_mesh_init

    model = MoeTransformerLM(cfg, num_experts=num_experts, **kw)
    return unit_mesh_init(
        lambda rng, tokens: model.init(rng, tokens)["params"],
        jax.random.PRNGKey(seed),
        jnp.zeros((1, sample_len), jnp.int32),
    )


def build_moe_lm_train_step(
    cfg: TransformerConfig,
    num_experts: int,
    tx,
    mesh: Mesh,
    params_template: Any,
    aux_weight: float = 0.01,
    donate: bool = True,
    **kw,
):
    """step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, metrics)  # loss + aux

    DP over 'data' × EP over ``ep_axis`` in one program. Gradient sync is a
    data-axis mean only: expert grads are shard-owned (each ep shard owns
    distinct experts, and the all_to_all AD is exact), replicated-param grads
    come out identical on every ep shard.

    ``ep_axis`` may be any TOKEN-REPLICATED mesh axis — 'model' (default) or
    'pipe' on a 3-axis mesh whose pipeline axis is free. It may NOT be the
    'data' axis: this EP design dispatches the same replicated tokens from
    every ep shard (buying expert *memory* scaling), and its ÷ep gradient
    normalization is exact only for duplicate contributions. EP over the
    batch axis routes *distinct* tokens per shard — a different algorithm
    with a different gradient story (docs/DESIGN.md)."""
    ep_axis = kw.get("ep_axis", "model")
    if ep_axis == "data":
        raise ValueError(
            "build_moe_lm_train_step: ep_axis must be a token-replicated axis "
            "('model' or 'pipe'), not the batch axis 'data' — see docstring."
        )
    model = MoeTransformerLM(cfg, num_experts=num_experts, **kw)
    p_specs = moe_param_specs(params_template, ep_axis)
    o_specs = moe_param_specs(jax.eval_shape(tx.init, params_template), ep_axis)

    def _shard_step(params, opt_state, global_step, tokens, rng):
        # Dropout key: fold the global step and DATA-shard index only — model
        # shards must draw identical masks on the replicated activations.
        rng = jax.random.fold_in(
            jax.random.fold_in(rng, global_step), lax.axis_index("data")
        )

        def compute_loss(p):
            logits, aux = model.apply(
                {"params": p}, tokens, train=True,
                rngs={"dropout": rng} if cfg.dropout_rate else None,
            )
            return next_token_loss(logits, tokens) + aux_weight * aux, aux

        (loss, aux), grads = jax.value_and_grad(compute_loss, has_aux=True)(params)

        # Every ep shard dispatches the SAME (replicated) tokens, so each
        # expert processes its tokens once per shard and its owner's
        # gradient accumulates ep duplicate contributions — normalize by the
        # axis size (the duplicate compute itself is wall-clock neutral:
        # per-shard expert work is E·cap tokens regardless of ep; EP buys
        # expert MEMORY scaling). Replicated params need no ep collective.
        ep_size = lax.axis_size(ep_axis)

        def sync(path, g):
            names = [q.key for q in path if hasattr(q, "key")]
            if names and names[-1] in ("w_in", "b_in", "w_out", "b_out"):
                g = g / ep_size
            return lax.pmean(g, "data")

        grads = jax.tree_util.tree_map_with_path(sync, grads)
        loss = lax.pmean(loss, "data")
        aux = lax.pmean(aux, "data")
        grads = fence_grads(grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, new_opt, global_step + 1, {"loss": loss, "aux": aux}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(p_specs, o_specs, P(), P("data", None), P()),
        out_specs=(p_specs, o_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def moe_param_specs(tree: Any, ep_axis: str = "model") -> Any:
    """Expert-stacked leaves (w_in/b_in/w_out/b_out) sharded on dim 0 over
    ``ep_axis``; router and everything else replicated."""

    def spec(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        names = [p.key for p in path if hasattr(p, "key")]
        if names and names[-1] in ("w_in", "b_in", "w_out", "b_out"):
            return P(ep_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def shard_moe_params(
    tree: Any, mesh: Mesh, specs: Any | None = None, ep_axis: str = "model"
) -> Any:
    from distributed_tensorflow_tpu.parallel.data_parallel import place_by_specs

    return place_by_specs(
        tree, mesh, specs if specs is not None else moe_param_specs(tree, ep_axis)
    )


def init_moe_params(
    cfg: TransformerConfig, num_experts: int, seed: int = 0, sample_tokens: int = 8, **kw
) -> Any:
    """GLOBAL-shape host params (expert dim = full E): init runs inside a
    trivial 1×1 shard_map (the module queries ``lax.axis_size``)."""
    from distributed_tensorflow_tpu.parallel.mesh import unit_mesh_init

    layer = MoeMlp(cfg, num_experts=num_experts, **kw)
    return unit_mesh_init(
        lambda rng, x: layer.init(rng, x)["params"],
        jax.random.PRNGKey(seed),
        jnp.zeros((sample_tokens, cfg.d_model), jnp.float32),
    )


def build_moe_layer_fn(
    cfg: TransformerConfig, num_experts: int, mesh: Mesh, params_template: Any, **kw
):
    """Jitted shard_map apply: (params, x_local_tokens) -> (y, aux_loss).
    x (N, D) sharded over 'data', replicated over 'model'; expert params per
    :func:`moe_param_specs`. Gradient note for callers differentiating
    through this fn: replicated params (router) come out identical on every
    shard, but expert-leaf grads accumulate one duplicate contribution per
    model shard (every shard dispatches the same replicated tokens) — divide
    them by the axis size before use, as ``build_moe_lm_train_step`` does."""
    if kw.get("ep_axis", "model") == "data":
        raise ValueError(
            "build_moe_layer_fn: ep_axis must be a token-replicated axis "
            "('model' or 'pipe'), not the batch axis 'data' — this layer "
            "dispatches replicated tokens (see build_moe_lm_train_step)."
        )
    layer = MoeMlp(cfg, num_experts=num_experts, **kw)
    specs = moe_param_specs(params_template, kw.get("ep_axis", "model"))

    def _apply(params, x):
        y, aux = layer.apply({"params": params}, x)
        return y, lax.pmean(aux, "data")

    return jax.jit(
        jax.shard_map(
            _apply,
            mesh=mesh,
            in_specs=(specs, P(("data",), None)),
            out_specs=(P(("data",), None), P()),
            check_vma=False,
        )
    )
