from distributed_tensorflow_tpu.parallel.mesh import make_mesh  # noqa: F401
from distributed_tensorflow_tpu.parallel.data_parallel import (  # noqa: F401
    build_eval_step,
    build_train_step,
    replicate,
    shard_batch,
)
