"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context training shards the **sequence** dimension across devices: each
device holds a contiguous Sq/P slice of q/k/v. Exact attention then needs
every (q-shard, kv-shard) pair; ring attention streams the kv shards around
the mesh axis with ``lax.ppermute`` (P-1 hops over ICI) while each device
folds the visiting block into its local online-softmax state — communication
overlaps compute, memory stays O(S/P · block), and the result is bit-for-bit
the same softmax as dense attention over the full sequence.

This is the TPU-native shape of the technique (Liu et al., "Ring Attention
with Blockwise Transformers", 2023): collectives over the mesh axis instead
of point-to-point NCCL sends. The reference has no sequence models at all
(SURVEY §5.7) — this subsystem is framework-first-class rather than parity.

Use inside ``shard_map`` with the sequence axis sharded over ``axis_name``:

    mesh = make_mesh(...)   # e.g. axes ('data', 'model'); seq rides 'model'
    f = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name='model', causal=True),
        mesh=mesh,
        in_specs=P(None, None, 'model', None),   # (B, H, S, D) sharded on S
        out_specs=P(None, None, 'model', None),
    )
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.ops.attention import (
    NEG_INF,
    _finalize,
    _online_block_update,
    _scale,
)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
):
    """Exact attention over a sequence sharded on ``axis_name``.

    Must run inside ``shard_map``/``pmap``. ``q``/``k``/``v`` are the local
    shards, shape (B, H, S_local, D); shard i holds global positions
    [i·S_local, (i+1)·S_local). Returns the local (B, H, S_local, D) output.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    s = _scale(q, scale)
    q_pos = my_idx * s_local + lax.broadcasted_iota(jnp.int32, (s_local, 1), 0)
    # Shift kv one hop "left" each step: after t hops we hold the shard that
    # originated on device (my_idx + t) mod P.
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        acc, m, l, k_blk, v_blk = carry
        src = lax.rem(my_idx + t, axis_size)
        k_pos = src * s_local + lax.broadcasted_iota(jnp.int32, (1, s_local), 1)
        mask = jnp.ones((s_local, s_local), jnp.bool_) if not causal else (k_pos <= q_pos)
        acc, m, l = _online_block_update((acc, m, l), q, k_blk, v_blk, mask, s)
        # Unconditional permute (the last hop returns shards home): collectives
        # under lax.cond don't lower cleanly in SPMD, and one extra hop is
        # cheaper than a branch.
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (acc, m, l, k_blk, v_blk), None

    init = (
        jnp.zeros((b, h, s_local, d), jnp.float32),
        jnp.full((b, h, s_local), NEG_INF, jnp.float32),
        jnp.zeros((b, h, s_local), jnp.float32),
        k,
        v,
    )
    (acc, _, l, _, _), _ = lax.scan(step, init, jnp.arange(axis_size))
    return _finalize(acc, l, q.dtype)
