"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context training shards the **sequence** dimension across devices: each
device holds a contiguous Sq/P slice of q/k/v. Exact attention then needs
every (q-shard, kv-shard) pair; ring attention streams the kv shards around
the mesh axis with ``lax.ppermute`` (P-1 hops over ICI) while each device
folds the visiting block into its local online-softmax state — communication
overlaps compute, memory stays O(S/P · block), and the result is bit-for-bit
the same softmax as dense attention over the full sequence.

This is the TPU-native shape of the technique (Liu et al., "Ring Attention
with Blockwise Transformers", 2023): collectives over the mesh axis instead
of point-to-point NCCL sends. The reference has no sequence models at all
(SURVEY §5.7) — this subsystem is framework-first-class rather than parity.

Use inside ``shard_map`` with the sequence axis sharded over ``axis_name``:

    mesh = make_mesh(...)   # e.g. axes ('data', 'model'); seq rides 'model'
    f = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name='model', causal=True),
        mesh=mesh,
        in_specs=P(None, None, 'model', None),   # (B, H, S, D) sharded on S
        out_specs=P(None, None, 'model', None),
    )
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.ops.attention import (
    NEG_INF,
    _finalize,
    _online_block_update,
    _scale,
)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
    window: int | None = None,
):
    """Exact attention over a sequence sharded on ``axis_name``.

    Must run inside ``shard_map``/``pmap``. ``q``/``k``/``v`` are the local
    shards, shape (B, H, S_local, D); shard i holds global positions
    [i·S_local, (i+1)·S_local). Returns the local (B, H, S_local, D) output.

    ``window`` (requires ``causal``): sliding-window attention with the same
    Mistral semantics as the single-device tiers — and the ring TRUNCATES:
    query positions in shard i only see keys back to shard
    ``i − ceil((window−1)/S_local)``, so the scan runs
    ``min(P, ceil((window−1)/S_local) + 1)`` hops instead of P, the kv
    stream rotated toward DESCENDING source shards. Ring communication and
    compute drop from O(S) to O(window) per device — the property that
    makes window+SP the long-context configuration rather than two features
    that cancel. Hops that would wrap past shard 0 carry nothing causal and
    skip their block update under ``lax.cond`` (the ppermute itself stays
    unconditional — collectives must run on every shard).
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    s = _scale(q, scale)
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    q_pos = my_idx * s_local + lax.broadcasted_iota(jnp.int32, (s_local, 1), 0)

    if window is None:
        # Shift kv one hop "left" each step: after t hops we hold the shard
        # that originated on device (my_idx + t) mod P.
        perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        n_hops = axis_size
        src_of = lambda t: lax.rem(my_idx + t, axis_size)
    else:
        # Windowed: rotate the OTHER way so hop t delivers shard
        # my_idx − t — the window only ever looks backward, and the first
        # out-of-window shard ends the (statically truncated) scan.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        back = 0 if window == 1 else -(-(window - 1) // s_local)
        n_hops = min(axis_size, back + 1)
        src_of = lambda t: lax.rem(my_idx - t + axis_size, axis_size)

    def step(carry, t):
        acc, m, l, k_blk, v_blk = carry
        src = src_of(t)
        k_pos = src * s_local + lax.broadcasted_iota(jnp.int32, (1, s_local), 1)
        if not causal:
            mask = jnp.ones((s_local, s_local), jnp.bool_)
        else:
            mask = k_pos <= q_pos
            if window is not None:
                mask &= k_pos > q_pos - window
        if window is None:
            acc, m, l = _online_block_update((acc, m, l), q, k_blk, v_blk, mask, s)
        else:
            # Shards before shard 0 don't exist: a hop that wrapped past the
            # sequence start (t > my_idx) is entirely masked — skip the two
            # dots, keep the ppermute below unconditional.
            acc, m, l = lax.cond(
                t <= my_idx,
                lambda c: _online_block_update(c, q, k_blk, v_blk, mask, s),
                lambda c: c,
                (acc, m, l),
            )
        # Unconditional permute (full ring: the last hop returns shards
        # home; windowed: the final rotation is discarded with the carry):
        # collectives under lax.cond don't lower cleanly in SPMD, and one
        # extra hop is cheaper than a branch.
        k_blk, v_blk = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (acc, m, l, k_blk, v_blk), None

    init = (
        jnp.zeros((b, h, s_local, d), jnp.float32),
        jnp.full((b, h, s_local), NEG_INF, jnp.float32),
        jnp.zeros((b, h, s_local), jnp.float32),
        k,
        v,
    )
    (acc, _, l, _, _), _ = lax.scan(step, init, jnp.arange(n_hops))
    return _finalize(acc, l, q.dtype)
