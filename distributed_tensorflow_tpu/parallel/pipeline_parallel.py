"""Pipeline parallelism (GPipe-style microbatching) over the mesh's
``model`` axis.

The reference has no pipeline parallelism (SURVEY §2.3); this module is the
PP leg of the framework's five-axis coverage (DP / TP / SP / PP / EP) on the
same two-axis mesh. Design:

  * the transformer's blocks are split into S = axis_size('model') stages;
    each stage's block parameters are STACKED along a leading stage dim and
    sharded ``P('model')`` — device s holds only its own layers;
  * the batch is split into M microbatches; a ``lax.scan`` over
    M + S - 1 ticks drives the classic GPipe schedule: stage 0 ingests
    microbatch t, every stage applies its layers, activations hop to the
    next stage via ``lax.ppermute`` (differentiable — the backward pass
    hops in reverse automatically);
  * embeddings / final-norm / LM head are replicated. Embedding gradients
    are live only through stage 0's masked ingest path (every other shard
    contributes exact zeros) and are ``psum``-ed over 'model'; final-norm and
    head gradients are computed from the broadcast (replicated) outputs and
    come out identical on every shard — no collective needed there.

Numerics are verified by an exact-parity test against the plain
``TransformerLM`` with the same (re-stacked) weights — see
``tests/test_pipeline_parallel.py``.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.models.transformer import (
    Block,
    TransformerConfig,
    _attention_fn,
    next_token_loss,
)
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

__all__ = [
    "stack_stage_params",
    "pp_param_specs",
    "shard_pp_params",
    "build_pp_lm_train_step",
]


def _collect_from_last(x, mask, axis: str):
    """Broadcast the last stage's collected outputs to every shard: forward
    ``psum(x * mask)`` (all other shards contribute zeros), backward delivers
    the cotangent ONLY to the last stage (``t * mask``), unscaled. A raw psum
    would multiply the pipeline's entire backward by the stage count (its
    shard_map transpose is another psum — same pitfall as tensor_parallel's
    ``_reduce_from_tp``)."""

    @jax.custom_vjp
    def f(v, m):
        return lax.psum(v * m, axis)

    def fwd(v, m):
        return lax.psum(v * m, axis), m

    def bwd(m, t):
        return (t * m, None)

    f.defvjp(fwd, bwd)
    return f(x, mask)


def _split_tree(params: dict, keys: tuple[str, ...]) -> tuple[dict, dict]:
    inside = {k: v for k, v in params.items() if k in keys}
    outside = {k: v for k, v in params.items() if k not in keys}
    return inside, outside


def stack_stage_params(lm_params: dict, num_stages: int) -> dict:
    """Regroup a plain ``TransformerLM`` param tree for the pipeline:
    ``block_i`` subtrees are stacked twice — layers-per-stage inside each
    stage, stages on the leading dim → leaves ``(S, L/S, ...)``. Embeddings,
    final norm, and head stay as-is (replicated)."""
    block_names = sorted(
        (k for k in lm_params if k.startswith("block_")),
        key=lambda k: int(k.split("_")[1]),
    )
    n = len(block_names)
    if n % num_stages:
        raise ValueError(f"{n} layers not divisible into {num_stages} stages")
    per = n // num_stages
    blocks, rest = _split_tree(lm_params, tuple(block_names))

    def stack(trees):
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees)

    stages = stack(
        [
            stack([blocks[block_names[s * per + l]] for l in range(per)])
            for s in range(num_stages)
        ]
    )
    return {"stages": stages, **rest}


def unstack_stage_params(pp_params: dict) -> dict:
    """Inverse of :func:`stack_stage_params`: back to the plain
    ``TransformerLM`` tree (for export / checkpoint interchange)."""
    stages = jax.tree_util.tree_map(np.asarray, jax.device_get(pp_params["stages"]))
    rest = {k: v for k, v in pp_params.items() if k != "stages"}
    sample = jax.tree_util.tree_leaves(stages)[0]
    num_stages, per = sample.shape[0], sample.shape[1]
    out = dict(jax.device_get(rest))
    for s in range(num_stages):
        for l in range(per):
            out[f"block_{s * per + l}"] = jax.tree_util.tree_map(
                lambda v: v[s, l], stages
            )
    return out


def pp_param_specs(tree: Any) -> Any:
    """'stages' subtree sharded on its leading (stage) dim; everything else
    replicated. Works for optimizer-state trees too (path-suffix match)."""

    def spec(path, leaf):
        if getattr(leaf, "ndim", None) == 0:
            return P()
        names = [p.key for p in path if hasattr(p, "key")]
        return P("model") if "stages" in names else P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def shard_pp_params(tree: Any, mesh: Mesh, specs: Any | None = None) -> Any:
    """Place a stacked-stage param/opt tree (see ``data_parallel.place_by_specs``)."""
    from distributed_tensorflow_tpu.parallel.data_parallel import place_by_specs

    return place_by_specs(tree, mesh, specs if specs is not None else pp_param_specs(tree))


def build_pp_lm_train_step(
    cfg: TransformerConfig,
    tx,
    mesh: Mesh,
    params_template: Any,
    num_microbatches: int,
    loss_fn: Callable = next_token_loss,
    donate: bool = True,
    pp_axis: str = "model",
):
    """step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, metrics)

    ``params`` is a :func:`stack_stage_params` tree placed with
    :func:`shard_pp_params`; ``tokens`` (B, T) sharded over 'data' with
    B divisible by ``num_microbatches``.
    """
    # Dropout note: masks are drawn per (stage, tick) inside the schedule, so
    # they are valid-but-different from an unpipelined run's masks (exact
    # parity with the plain model holds at dropout_rate == 0, as tested).
    stage_leaf = jax.tree_util.tree_leaves(params_template["stages"])[0]
    if stage_leaf.shape[0] != mesh.shape[pp_axis]:
        raise ValueError(
            f"params stacked for {stage_leaf.shape[0]} stages but mesh "
            f"'{pp_axis}' axis has {mesh.shape[pp_axis]} shards"
        )
    p_specs = pp_param_specs(params_template)
    o_specs = pp_param_specs(jax.eval_shape(tx.init, params_template))
    block = Block(cfg)
    embed_mod = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype)
    pos_mod = nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.compute_dtype)
    ln_f = nn.LayerNorm(dtype=cfg.compute_dtype)
    head = nn.Dense(
        cfg.vocab_size, dtype=cfg.compute_dtype,
        use_bias=cfg.use_bias,
    )
    attend = _attention_fn(cfg, prefer_packed=True)
    M = num_microbatches

    def forward(params, tokens, rng_drop):
        S = lax.axis_size(pp_axis)
        stage = lax.axis_index(pp_axis)
        # Decorrelate dropout across stages too: within one tick, different
        # stages process different microbatches at different depths — the
        # distinct stage params do NOT decorrelate the RNG stream by
        # themselves.
        rng_drop = jax.random.fold_in(rng_drop, stage)
        b, t = tokens.shape
        if b % M:
            raise ValueError(f"local batch {b} not divisible into {M} microbatches")
        bm = b // M

        # Replicated embedding of ALL microbatches (only stage 0's ingest
        # path keeps it live — see the where() below).
        x = embed_mod.apply({"params": params["tok_embed"]}, tokens)
        rope = getattr(cfg, "position", "learned") == "rope"
        if not rope:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
            x = x + pos_mod.apply({"params": params["pos_embed"]}, positions)
        micro = x.reshape(M, bm, t, cfg.d_model)
        # Under RoPE every microbatch spans the full sequence, so blocks
        # rotate by the same arange(t) positions — the sublayer's default;
        # no positions need threading through the schedule.

        my_stage = jax.tree_util.tree_map(
            lambda v: jnp.squeeze(v, 0), params["stages"]
        )  # (L/S, ...) local layers

        n_local_layers = jax.tree_util.tree_leaves(my_stage)[0].shape[0]

        def apply_one(h, layer_params, layer_key):
            return block.apply(
                {"params": layer_params}, h, attend, train=cfg.dropout_rate > 0,
                rngs={"dropout": layer_key} if cfg.dropout_rate else None,
            )

        if cfg.remat:
            # Recompute each layer on backward: the scan otherwise saves every
            # layer's intermediates for all ticks of the schedule.
            apply_one = jax.checkpoint(apply_one)

        def apply_stage(h, key):
            def layer(h, xs):
                layer_params, i = xs
                return apply_one(h, layer_params, jax.random.fold_in(key, i)), None

            h, _ = lax.scan(layer, h, (my_stage, jnp.arange(n_local_layers)))
            return h

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        n_ticks = M + S - 1

        def tick(carry, ti):
            state, outputs = carry
            # Stage 0 ingests microbatch ti. During the S-1 drain ticks
            # (ti >= M) the clamped index re-processes microbatch M-1; that
            # compute is DISCARDED, not masked — its outputs land outside the
            # written window (tick t reaches the last stage at t+S-1 > the
            # final tick) and the final carry is dropped, so no spurious
            # contributions (or cotangents) exist. Keep that invariant if
            # changing the schedule.
            ingest = micro[jnp.minimum(ti, M - 1)]
            inp = jnp.where(stage == 0, ingest, state)
            out = apply_stage(inp, jax.random.fold_in(rng_drop, ti))
            # Last stage's tick ti output is microbatch ti-(S-1).
            mi = ti - (S - 1)
            write = jnp.logical_and(stage == S - 1, mi >= 0)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, out, outputs[jnp.maximum(mi, 0)]),
                jnp.maximum(mi, 0),
                axis=0,
            )
            state = lax.ppermute(out, pp_axis, fwd_perm)
            return (state, outputs), None

        init_outputs = jnp.zeros((M, bm, t, cfg.d_model), cfg.compute_dtype)
        (_, outputs), _ = lax.scan(
            tick,
            (jnp.zeros((bm, t, cfg.d_model), cfg.compute_dtype), init_outputs),
            jnp.arange(n_ticks),
        )
        # Broadcast the last stage's collected activations to every shard
        # (all other shards hold zeros).
        mask = jnp.where(stage == S - 1, 1.0, 0.0).astype(outputs.dtype)
        outputs = _collect_from_last(outputs, mask, pp_axis)
        h = outputs.reshape(b, t, cfg.d_model)
        h = ln_f.apply({"params": params["ln_f"]}, h)
        return head.apply({"params": params["lm_head"]}, h).astype(jnp.float32)

    def _shard_step(params, opt_state, global_step, tokens, rng):
        # Per-step, per-data-shard base key; forward() folds in the stage
        # index and the tick so every (stage, tick, layer) draws a distinct
        # mask.
        rng = jax.random.fold_in(
            jax.random.fold_in(rng, global_step), lax.axis_index("data")
        )

        def compute_loss(p):
            return loss_fn(forward(p, tokens, rng), tokens)

        loss, grads = jax.value_and_grad(compute_loss)(params)

        # Gradient sync by param group:
        #   stages    — shard-owned; cotangents arrived via the reversed
        #               ppermute chain, no model collective needed;
        #   embeddings— live only through stage 0's masked ingest path (other
        #               shards contribute exact zeros) -> psum over 'model';
        #   ln_f/head — computed from replicated activations with a
        #               replicated cotangent -> already identical, no-op.
        # Then the data-parallel mean.
        def sync(path, g):
            names = [q.key for q in path if hasattr(q, "key")]
            if "tok_embed" in names or "pos_embed" in names:
                g = lax.psum(g, pp_axis)
            return lax.pmean(g, "data")

        grads = jax.tree_util.tree_map_with_path(sync, grads)
        loss = lax.pmean(loss, "data")
        grads = fence_grads(grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, new_opt, global_step + 1, {"loss": loss}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(p_specs, o_specs, P(), P("data", None), P()),
        out_specs=(p_specs, o_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)