"""Fully-sharded data parallelism (FSDP / ZeRO-3) over the device mesh.

The closest TPU-native analog of the reference's parameter-server variable
placement: ``replica_device_setter`` round-robins each variable onto a ps
task (``demo2/train.py:27-29``), so no single process holds the whole model,
and every step a worker *reads* the variables over gRPC and *pushes* gradient
updates back (``demo2/train.py:176-193``). Here the "parameter store" is the
mesh itself: every parameter (and its optimizer state — the 2× Adam moments
are the big win) lives **sharded 1/N per device**, an ``all_gather`` over ICI
materialises full weights just-in-time for compute (the variable read), and a
``psum_scatter`` (reduce-scatter) delivers each device only its own gradient
shard (the gradient push). Unlike the reference's async HogWild applies, the
update is synchronous and bitwise-identical across the mesh.

Layout: each param leaf is flattened, padded to a multiple of the mesh size,
and stored as an ``(n_devices, chunk)`` array sharded ``P(('data','model'))``
on dim 0 — one ``(1, chunk)`` block per device. Optimizer state built over
the chunked tree shards the same way (elementwise optimizers like Adam/SGD
act identically on any partition of the flattened params, so per-shard
updates equal the corresponding shard of the full update; optax scalars such
as the step count stay replicated). Gradient mean + partition is ONE fused
collective (``lax.psum_scatter``) instead of the all-reduce every device in
plain DP pays; **persistent** per-device memory is ``(params + opt state)/N``
— the dominant term for Adam (3× params in f32). Honest scope note: this
implementation gathers the whole param tree per step, so full params + full
grads still coexist transiently during fwd/bwd — the peak-memory profile of
ZeRO-1/2, not a per-layer-gather ZeRO-3; what it buys is the 1/N persistent
state (and the fused reduce-scatter), not training a model whose weights
alone exceed one chip's HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.ops.losses import accuracy, softmax_cross_entropy
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

# Params are sharded over the FLATTENED mesh — both axes act as one FSDP
# axis, matching data_parallel's batch sharding over ('data','model').
AXES = ("data", "model")


def _chunk(x: np.ndarray, n: int) -> np.ndarray:
    """Flatten → pad to a multiple of n → (n, chunk)."""
    flat = np.asarray(x).reshape(-1)
    c = -(-flat.size // n)
    if c * n != flat.size:
        flat = np.concatenate([flat, np.zeros(c * n - flat.size, flat.dtype)])
    return flat.reshape(n, c)


def chunk_tree(tree: Any, mesh: Mesh) -> Any:
    """Host-side: rechunk every array leaf to ``(n_devices, chunk)``. Scalar
    leaves (e.g. optax's step count) pass through unchanged."""
    n = mesh.devices.size
    return jax.tree_util.tree_map(
        lambda x: x if np.ndim(x) == 0 else _chunk(x, n), tree
    )


def _chunked_spec(mesh: Mesh, shape) -> P:
    n = mesh.devices.size
    return P(AXES) if len(shape) == 2 and shape[0] == n else P()


def chunked_specs(mesh: Mesh, chunked_shapes: Any) -> Any:
    """PartitionSpec tree for a chunked state tree: ``(n, chunk)`` leaves
    sharded one block per device, scalars replicated."""
    return jax.tree_util.tree_map(
        lambda s: _chunked_spec(mesh, np.shape(s) if not hasattr(s, "shape") else s.shape),
        chunked_shapes,
    )


def place_chunked(tree: Any, mesh: Mesh) -> Any:
    """Place a chunked host tree per :func:`chunked_specs`. Multi-process:
    every process passes the same full host values (chief-seeded init or a
    restored checkpoint), each contributing its own devices' blocks."""
    from distributed_tensorflow_tpu.parallel.data_parallel import place_by_specs

    return place_by_specs(tree, mesh, chunked_specs(mesh, tree))


def shard_fsdp_params(params: Any, mesh: Mesh) -> Any:
    """Chunk + place a host param tree (each device holds 1/N of every leaf)."""
    return place_chunked(chunk_tree(params, mesh), mesh)


def init_fsdp_opt_state(tx, params_host: Any, mesh: Mesh) -> Any:
    """Optimizer state over the CHUNKED params: moment leaves mirror the
    ``(n, chunk)`` layout and shard with the params; scalars replicate."""
    return place_chunked(
        jax.device_get(tx.init(chunk_tree(params_host, mesh))), mesh
    )


def gather_fsdp_params(params_sharded: Any, template: Any) -> Any:
    """Host-side inverse of :func:`shard_fsdp_params` (checkpoint/export):
    fetch, unpad, reshape back to the template's shapes."""
    host = jax.device_get(params_sharded)
    return jax.tree_util.tree_map(
        lambda x, t: np.asarray(x)
        .reshape(-1)[: np.asarray(t).size]
        .reshape(np.shape(t))
        .astype(np.asarray(t).dtype),
        host,
        template,
    )


def _build_step(
    loss_and_metrics: Callable,
    tx,
    mesh: Mesh,
    template: Any,
    batch_spec: Any,
    donate: bool,
):
    """Shared FSDP step core.

    ``loss_and_metrics(full_params, batch, rng) -> (loss, metrics)`` runs on
    each device's batch shard against just-in-time gathered full params.
    ``template`` is a host param tree (or ShapeDtypeStructs) giving the
    ORIGINAL (unchunked) leaf shapes.
    """
    n = mesh.devices.size
    shapes = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if not isinstance(x, jax.ShapeDtypeStruct)
        else x,
        template,
    )
    # Mirror chunk_tree exactly: array leaves -> (n, ceil(size/n)), scalar
    # leaves pass through (replicated) — the two MUST agree or the shard_map
    # specs mismatch the placed state.
    chunked_shapes = jax.tree_util.tree_map(
        lambda s: s
        if not s.shape
        else jax.ShapeDtypeStruct((n, -(-int(np.prod(s.shape)) // n)), s.dtype),
        shapes,
    )
    params_specs = chunked_specs(mesh, chunked_shapes)
    opt_shapes = jax.eval_shape(tx.init, chunked_shapes)
    opt_specs = chunked_specs(mesh, opt_shapes)

    def gather_full(local):
        # The "variable read": (1, chunk) blocks -> full leaf shapes.
        # Scalar leaves are replicated, not chunked — pass through.
        def g(x, s):
            if not s.shape:
                return x
            full = lax.all_gather(x, AXES, tiled=True).reshape(-1)
            return full[: int(np.prod(s.shape))].reshape(s.shape)

        return jax.tree_util.tree_map(g, local, shapes)

    def scatter_grad_mean(full):
        # The "gradient push": fused mean-over-devices + partition — each
        # device receives only its own (1, chunk) gradient shard. Scalar
        # (replicated) leaves take a plain pmean.
        def s(gr, sds):
            if not sds.shape:
                return lax.pmean(gr, AXES)
            size = int(np.prod(sds.shape))
            c = -(-size // n)
            flat = gr.reshape(-1)
            if c * n != size:
                flat = jnp.concatenate([flat, jnp.zeros((c * n - size,), flat.dtype)])
            return (
                lax.psum_scatter(
                    flat.reshape(n, c), AXES, scatter_dimension=0, tiled=False
                )
                / n
            )[None]

        return jax.tree_util.tree_map(s, full, shapes)

    def _shard_step(params, opt_state, global_step, batch, rng):
        # Same per-step/per-shard RNG discipline as data_parallel.
        from distributed_tensorflow_tpu.parallel.data_parallel import _shard_index

        rng = jax.random.fold_in(
            jax.random.fold_in(rng, global_step), _shard_index(AXES)
        )

        # Gather OUTSIDE the diff: grads are taken w.r.t. the full params and
        # reduce-scattered explicitly — the communication pattern is the
        # code, not an autodiff transpose.
        full = gather_full(params)

        def compute(full_p):
            return loss_and_metrics(full_p, batch, rng)

        (loss, metrics), grads_full = jax.value_and_grad(compute, has_aux=True)(full)
        grads = scatter_grad_mean(grads_full)
        metrics = {k: lax.pmean(v, AXES) for k, v in metrics.items()}
        metrics["loss"] = lax.pmean(loss, AXES)
        grads = fence_grads(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, global_step + 1, metrics

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(params_specs, opt_specs, P(), batch_spec, P()),
        out_specs=(params_specs, opt_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)


def build_fsdp_train_step(
    apply_fn: Callable,
    tx,
    mesh: Mesh,
    template: Any,
    loss_fn: Callable = softmax_cross_entropy,
    donate: bool = True,
):
    """FSDP train step for image-classifier batches ``{'image','label'}``
    (same call signature/semantics as ``data_parallel.build_train_step``, but
    params/opt-state enter CHUNKED — see :func:`shard_fsdp_params`).

    step(params, opt_state, global_step, batch, rng)
        -> (params, opt_state, global_step, metrics)
    """

    def loss_and_metrics(full_params, batch, rng):
        logits = apply_fn(
            {"params": full_params}, batch["image"], train=True, rngs={"dropout": rng}
        )
        return loss_fn(logits, batch["label"]), {
            "accuracy": accuracy(logits, batch["label"])
        }

    return _build_step(loss_and_metrics, tx, mesh, template, P(AXES), donate)


def build_fsdp_lm_train_step(
    cfg,
    tx,
    mesh: Mesh,
    template: Any,
    donate: bool = True,
):
    """FSDP train step for the TransformerLM: batch data-parallel over the
    flattened mesh, every weight + Adam moment sharded 1/N per device.

    step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, {'loss'})
    """
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerLM,
        next_token_loss,
    )

    model = TransformerLM(cfg)

    def loss_and_metrics(full_params, tokens, rng):
        logits = model.apply(
            {"params": full_params}, tokens, train=True, rngs={"dropout": rng}
        )
        return next_token_loss(logits, tokens), {}

    return _build_step(loss_and_metrics, tx, mesh, template, P(AXES, None), donate)
