"""Multi-process coordination.

Replaces the reference's ``tf.train.ClusterSpec`` / ``tf.train.Server`` gRPC
runtime and ``Supervisor`` chief election (``demo2/train.py:11-29,166-176``):

  * process group        → ``jax.distributed.initialize`` (coordinator =
    first worker host, parity with the reference's chief = task_index 0)
  * parameter servers    → none. Parameters live replicated/sharded in HBM;
    gradient sync is an XLA collective over ICI/DCN. A ``--job_name=ps``
    launch is accepted and exits with an explanation (the process simply has
    no role to play — ps hosts in the reference block in ``server.join()``
    forever, ``demo2/train.py:23-24``).
  * chief responsibilities (init/ckpt/summaries) → ``jax.process_index()==0``.
"""

from __future__ import annotations

import os

import jax

from distributed_tensorflow_tpu.config import ClusterConfig
from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _maybe_enable_cpu_collectives() -> None:
    """Cross-process collectives on the CPU backend need an explicit
    implementation on older jaxlibs (gloo); without it every cross-host psum
    dies with "Multiprocess computations aren't implemented on the CPU
    backend". No-op on TPU/GPU platforms and on jax versions that select the
    implementation automatically."""
    platforms = str(getattr(jax.config, "jax_platforms", None) or "") or os.environ.get(
        "JAX_PLATFORMS", ""
    )
    if "cpu" not in platforms:
        return
    try:
        if not getattr(jax.config, "jax_cpu_collectives_implementation", None):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # option absent/renamed on this jax version
        pass


def initialize_from_cluster(cluster: ClusterConfig) -> bool:
    """Initialize the JAX process group from reference-style cluster flags.

    Returns False (after logging) for ``--job_name=ps`` — the caller should
    exit: there are no parameter servers in a synchronous SPMD runtime.

    ``cluster.initialization_timeout`` bounds the wait for stragglers: a
    worker that never joins (preempted before start, wrong address) makes
    ``jax.distributed.initialize`` raise after that many seconds instead of
    the job hanging forever — fail loudly, then let the scheduler retry."""
    if cluster.job_name == "ps":
        log.info(
            "job_name=ps accepted for CLI parity but parameter servers do not "
            "exist on TPU: parameters are device-resident and gradients are "
            "all-reduced over ICI. This process has nothing to do; exiting."
        )
        return False
    if cluster.num_processes > 1:
        if jax.distributed.is_initialized():
            # Already in a group (repeated main() calls, e.g. a resume in
            # the same process) — initialize would raise. NOTE: must not
            # probe via jax.process_count(): that itself initialises the
            # XLA backend, which forbids a later initialize().
            return True
        _maybe_enable_cpu_collectives()
        kwargs = {}
        timeout = int(getattr(cluster, "initialization_timeout", 0) or 0)
        if timeout > 0:
            import inspect

            if "initialization_timeout" in inspect.signature(
                jax.distributed.initialize
            ).parameters:
                kwargs["initialization_timeout"] = timeout
        jax.distributed.initialize(
            coordinator_address=cluster.coordinator_address,
            num_processes=cluster.num_processes,
            process_id=cluster.task_index,
            **kwargs,
        )
        log.info(
            "joined process group: process %d/%d, %d local / %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.local_device_count(),
            jax.device_count(),
        )
    return True


def is_chief() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-process sync point (Supervisor's wait-for-chief-init analog)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
