"""Tensor parallelism (Megatron-style) over the mesh's ``model`` axis.

The reference has no tensor parallelism (SURVEY §2.3 — its only distribution
is async data parallelism), but the framework's mesh reserves a ``model``
axis; this module makes it a first-class compute axis: attention heads and
the MLP hidden dimension are sharded across it, with the two canonical
all-reduces per block (after the attention output projection and after the
MLP down-projection) expressed as explicit ``lax.psum`` collectives riding
ICI — same shard_map-with-visible-collectives philosophy as
``data_parallel.py``.

Sharding rules (the Megatron recipe):

    q kernel        (D, D)      column-parallel  P(None, 'model') → local heads
    k/v kernels     (D, KV·dh)  column-parallel  P(None, 'model') → local kv
                    (GQA: kv heads shard WITH their query groups — whole
                    groups stay shard-local, so attention itself needs no
                    communication; requires num_kv_heads % tp == 0)
    attn out proj   (D, D)      row-parallel     P('model', None) → psum
    mlp_in kernel   (D, F)      column-parallel  P(None, 'model')
    mlp_out kernel  (F, D)      row-parallel     P('model', None) → psum
    embeddings, layer norms, lm head, row-parallel biases: replicated

Gradients: the model axis needs no gradient collective at all — the backward
``psum`` lives inside the forward graph (Megatron's ``f``: identity forward /
psum backward at each column-parallel branch input, :func:`_copy_to_tp`), so
sharded-param grads are shard-owned and replicated-param grads come out
identical on every shard. Only the data-parallel mean crosses the ``data``
axis.

:class:`TpTransformerLM` keeps separate q/k/v projections (a fused qkv kernel
cannot be contiguously column-sharded without interleaving the q/k/v blocks).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig,
    _attention_fn,
    next_token_loss,
)
from distributed_tensorflow_tpu.ops.rope import apply_rope, rope_tables
from distributed_tensorflow_tpu.parallel.data_parallel import fence_grads

__all__ = [
    "TpTransformerLM",
    "tp_param_specs",
    "shard_params",
    "build_tp_lm_train_step",
]


def _copy_to_tp(x, axis: str):
    """Megatron's ``f``: identity forward, ``psum`` backward. Placed at the
    input of every column-parallel branch so each shard's PARTIAL activation
    cotangent (it only backprops through its own columns) is summed into the
    full gradient right here — after which every replicated activation's (and
    therefore replicated parameter's) gradient is identical on all shards and
    needs no further model-axis sync."""

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (lax.psum(g, axis),)

    f.defvjp(fwd, bwd)
    return f(x)


def _reduce_from_tp(x, axis: str):
    """Megatron's ``g``, the conjugate of :func:`_copy_to_tp`: ``psum``
    forward (combine the row-parallel partial outputs), IDENTITY backward.
    A raw ``lax.psum`` must not be used here: under shard_map AD its
    transpose is another psum, which multiplies every branch cotangent by the
    axis size (measured: exactly ×tp grad inflation on the MLP path)."""

    @jax.custom_vjp
    def g_fn(v):
        return lax.psum(v, axis)

    def fwd(v):
        return lax.psum(v, axis), None

    def bwd(_, t):
        return (t,)

    g_fn.defvjp(fwd, bwd)
    return g_fn(x)


class TpBlock(nn.Module):
    cfg: TransformerConfig
    tp_axis: str = "model"

    @nn.compact
    def __call__(self, x, attend, train: bool = False, positions=None):
        cfg = self.cfg
        d = cfg.compute_dtype
        tp = lax.axis_size(self.tp_axis)
        if cfg.num_heads % tp:
            raise ValueError(f"num_heads {cfg.num_heads} not divisible by tp={tp}")
        kv_total = cfg.kv_heads
        if not (1 <= kv_total <= cfg.num_heads) or cfg.num_heads % kv_total:
            # Same malformed-GQA guard as attention_sublayer — TpBlock
            # bypasses it, and group = H // KV below would silently
            # mis-shape (group 0 or truncated) instead of erroring.
            raise ValueError(
                f"num_kv_heads must be in [1, num_heads] and divide it: "
                f"num_heads {cfg.num_heads} not divisible by num_kv_heads "
                f"{kv_total}"
            )
        if kv_total % tp:
            # GQA shards kv heads WITH their query groups: shard i owns q
            # heads [i·H/tp, (i+1)·H/tp) and kv heads [i·KV/tp, (i+1)·KV/tp)
            # — h // group lands in exactly that range, so every group is
            # shard-local and attention needs no kv communication. That
            # only tiles when tp divides num_kv_heads.
            raise ValueError(
                f"num_kv_heads {kv_total} not divisible by tp={tp}: tensor "
                "parallelism keeps whole query groups per shard, so the kv "
                "heads must tile over the model axis (pick num_kv_heads a "
                "multiple of tp, or shrink tp)"
            )
        local_heads = cfg.num_heads // tp
        local_kv = kv_total // tp
        group = cfg.num_heads // kv_total
        dh = cfg.d_model // cfg.num_heads

        h = _copy_to_tp(nn.LayerNorm(dtype=d, name="ln1")(x), self.tp_axis)
        b, s, _ = h.shape
        # Column-parallel projections: local kernels (D, D/tp) produce this
        # shard's heads directly — no communication in the forward here.
        # (features are the LOCAL width: flax validates stored-param shapes.)
        # Under GQA the k/v kernels are (D, KV·dh/tp) — the same narrower
        # projection the plain model's fused qkv Dense gets.
        bias = cfg.use_bias
        q = nn.Dense(cfg.d_model // tp, dtype=d, name="q", use_bias=bias)(h)
        k = nn.Dense(local_kv * dh, dtype=d, name="k", use_bias=bias)(h)
        v = nn.Dense(local_kv * dh, dtype=d, name="v", use_bias=bias)(h)
        q4 = q.reshape(b, s, local_heads, dh)
        k4 = k.reshape(b, s, local_kv, dh)
        if getattr(cfg, "position", "learned") == "rope":
            # RoPE rotates every head by the SAME position angles, so the
            # local head shard rotates exactly as it would unsharded — tp
            # parity is preserved without any collective.
            cos, sin = rope_tables(dh, s, cfg.rope_theta, positions=positions)
            q4 = apply_rope(q4, cos, sin)
            k4 = apply_rope(k4, cos, sin)
        if group > 1:
            # Local head sharing: each shard's query groups read their own
            # kv heads (whole groups are shard-local by construction).
            k4 = jnp.repeat(k4, group, axis=2)
            v4 = jnp.repeat(v.reshape(b, s, local_kv, dh), group, axis=2)
        else:
            v4 = v.reshape(b, s, local_kv, dh)
        to_heads = lambda t4: t4.transpose(0, 2, 1, 3)
        attn = attend(to_heads(q4), to_heads(k4), to_heads(v4))
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, local_heads * dh)
        # Row-parallel output projection: partial sums -> THE tp collective.
        # (proj/mlp_out biases, when enabled, are added AFTER the psum so
        # they aren't summed tp times — hence the explicit params.)
        attn = nn.Dense(cfg.d_model, use_bias=False, dtype=d, name="proj")(attn)
        attn = _reduce_from_tp(attn, self.tp_axis)
        if bias:
            attn = attn + self.param(
                "proj_bias", nn.initializers.zeros, (cfg.d_model,), jnp.float32
            ).astype(d)
        # Dropout on the REPLICATED (post-psum) activation: every model shard
        # draws the same mask from the same key, so tp parity is exact.
        if cfg.dropout_rate:
            attn = nn.Dropout(cfg.dropout_rate, deterministic=not train)(attn)
        x = x + attn

        h = _copy_to_tp(nn.LayerNorm(dtype=d, name="ln2")(x), self.tp_axis)
        h = nn.Dense(cfg.d_ff // tp, dtype=d, name="mlp_in", use_bias=bias)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, use_bias=False, dtype=d, name="mlp_out")(h)
        h = _reduce_from_tp(h, self.tp_axis)
        if bias:
            h = h + self.param(
                "mlp_out_bias", nn.initializers.zeros, (cfg.d_model,), jnp.float32
            ).astype(d)
        if cfg.dropout_rate:
            h = nn.Dropout(cfg.dropout_rate, deterministic=not train)(h)
        return x + h


class TpTransformerLM(nn.Module):
    """Tensor-parallel decoder LM. MUST run inside ``shard_map`` over a mesh
    that has ``tp_axis`` (size 1 degenerates to the plain model)."""

    cfg: TransformerConfig
    tp_axis: str = "model"

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = False):
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype, name="tok_embed")(
            tokens
        )
        rope = getattr(cfg, "position", "learned") == "rope"
        if not rope:
            x = x + nn.Embed(
                cfg.max_seq_len, cfg.d_model, dtype=cfg.compute_dtype, name="pos_embed"
            )(positions)
        # Heads are kernel-independent, so the plain model's attention
        # selection (dense/blockwise/flash/callable) applies unchanged to the
        # local head shard.
        attend = _attention_fn(cfg)
        # cfg.remat: recompute each block on backward (same trade as the
        # plain model; the in-block f/g collectives replay in lockstep on
        # every shard, so recomputation is SPMD-safe).
        block_cls = nn.remat(TpBlock, static_argnums=(2, 3)) if cfg.remat else TpBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, tp_axis=self.tp_axis, name=f"block_{i}")(
                x, attend, train, positions=positions if rope else None
            )
        x = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, dtype=cfg.compute_dtype, name="lm_head",
            use_bias=cfg.use_bias,
        )(x)
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Param sharding specs.
# ---------------------------------------------------------------------------

def tp_param_specs(tree: Any) -> Any:
    """PartitionSpec tree for a :class:`TpTransformerLM` param tree — also
    valid for optimizer-state trees whose leaves mirror param paths (Adam
    mu/nu); scalar leaves (e.g. Adam count) map to P().

    The split itself lives in ``parallel/rules.py::TP_TRAIN_RULES`` — one
    rule table shared with the serving engine's spec derivation instead of
    a second hand-wired path matcher."""
    from distributed_tensorflow_tpu.parallel.rules import (
        TP_TRAIN_RULES,
        match_partition_rules,
    )

    return match_partition_rules(TP_TRAIN_RULES, tree)


def _spec_for_path(path) -> P:
    """Per-PATH spec from the same rule table, for callers that resolve one
    tree_map_with_path entry at a time (``three_d`` stacks stage params and
    prefixes a 'pipe' axis onto the UNSTACKED dims' spec, so it cannot use
    the whole-tree resolver)."""
    import re

    from distributed_tensorflow_tpu.parallel.rules import TP_TRAIN_RULES

    name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
    for pattern, spec in TP_TRAIN_RULES:
        if re.search(pattern, name):
            return spec
    return P()


def shard_params(tree: Any, mesh: Mesh, specs: Any | None = None) -> Any:
    """Place a host param/opt tree according to its TP specs (every process
    passes the same full GLOBAL tree; see ``data_parallel.place_by_specs``)."""
    from distributed_tensorflow_tpu.parallel.data_parallel import place_by_specs

    return place_by_specs(tree, mesh, specs if specs is not None else tp_param_specs(tree))


# ---------------------------------------------------------------------------
# Train step: DP over 'data' × TP over 'model', one jitted program.
# ---------------------------------------------------------------------------


def init_tp_params(cfg: TransformerConfig, seed: int = 0, sample_len: int = 8) -> Any:
    """GLOBAL-shape host param tree for :class:`TpTransformerLM`.

    The module queries ``lax.axis_size`` so init must run inside shard_map;
    a trivial 1×1 ('data','model') mesh makes every local shape global."""
    from distributed_tensorflow_tpu.parallel.mesh import unit_mesh_init

    model = TpTransformerLM(cfg)
    return unit_mesh_init(
        lambda rng, tokens: model.init(rng, tokens)["params"],
        jax.random.PRNGKey(seed),
        jnp.zeros((1, sample_len), jnp.int32),
    )


def build_tp_lm_train_step(
    cfg: TransformerConfig,
    tx,
    mesh: Mesh,
    params_template: Any,
    loss_fn: Callable = next_token_loss,
    donate: bool = True,
):
    """step(params, opt_state, global_step, tokens, rng)
        -> (params, opt_state, global_step, metrics)

    ``tokens`` (B, S) sharded over 'data', replicated over 'model'; params
    and optimizer state sharded per :func:`tp_param_specs` (derive the
    placement with :func:`shard_params`). ``params_template`` is any
    host/abstract tree with the model's param structure — it only feeds spec
    derivation, no compute."""
    model = TpTransformerLM(cfg)
    p_specs = tp_param_specs(params_template)
    o_specs = tp_param_specs(jax.eval_shape(tx.init, params_template))

    def _shard_step(params, opt_state, global_step, tokens, rng):
        # Dropout key: fold the on-device global step and the DATA-shard index
        # only — model shards must draw identical masks (the dropout sites are
        # replicated activations; a per-model-shard mask would break the TP
        # replication invariant).
        rng = jax.random.fold_in(
            jax.random.fold_in(rng, global_step), lax.axis_index("data")
        )

        def compute_loss(p):
            logits = model.apply(
                {"params": p}, tokens, train=True,
                rngs={"dropout": rng} if cfg.dropout_rate else None,
            )
            return loss_fn(logits, tokens)

        loss, grads = jax.value_and_grad(compute_loss)(params)

        # Gradient sync: data-parallel mean only. The model axis needs no
        # grad collective — sharded params are wholly owned by their shard
        # (the row-parallel psum's VJP hands every shard the full output
        # cotangent), and replicated params' grads are already identical on
        # all shards thanks to _copy_to_tp's backward psum at branch inputs.
        grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, "data"), grads)
        loss = lax.pmean(loss, "data")
        grads = fence_grads(grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, new_opt, global_step + 1, {"loss": loss}

    shard_fn = jax.shard_map(
        _shard_step,
        mesh=mesh,
        in_specs=(p_specs, o_specs, P(), P("data", None), P()),
        out_specs=(p_specs, o_specs, P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1, 2) if donate else ()
    return jax.jit(shard_fn, donate_argnums=donate_args)
