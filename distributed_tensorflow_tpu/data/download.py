"""Model/dataset downloader — parity with the reference's
``maybe_download_and_extract`` (``retrain1/retrain.py:40-62``): fetch a
``.tgz`` with a progress meter if not already present, then extract into the
destination directory. Pure stdlib (urllib + tarfile); works for any URL
scheme urllib supports (https, file:// — the latter is what the offline test
environment uses).
"""

from __future__ import annotations

import hashlib
import os
import sys
import tarfile
import tempfile
import time
import urllib.request
from typing import Callable

from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.logging import get_logger
from distributed_tensorflow_tpu.utils.retry import retry_call

log = get_logger(__name__)

# The URL the reference hardcodes (retrain1/retrain.py:27).
INCEPTION_2015_URL = (
    "http://download.tensorflow.org/models/image/imagenet/inception-2015-12-05.tgz"
)


def ensure_dir_exists(dir_name: str) -> None:
    os.makedirs(dir_name, exist_ok=True)


# Probed ONCE at import (single-threaded): per-call probing would mutate
# process-global state and race other threads' file creation.
_UMASK = os.umask(0)
os.umask(_UMASK)


def sweep_stale_parts(
    dest_dir: str, name: str, max_age_secs: float = 3600.0
) -> list[str]:
    """Remove ``<name>.*.part`` temp files older than ``max_age_secs`` —
    debris from processes killed mid-download (mkstemp names are unique, so
    they accumulate forever otherwise). The age gate protects a concurrent
    LIVE downloader's temp file; a killed process's file only ages."""
    removed = []
    now = time.time()
    try:
        entries = os.listdir(dest_dir)
    except OSError:
        return removed
    for fn in entries:
        if not (fn.startswith(name + ".") and fn.endswith(".part")):
            continue
        path = os.path.join(dest_dir, fn)
        try:
            if now - os.stat(path).st_mtime >= max_age_secs:
                os.remove(path)
                removed.append(path)
        except OSError:
            continue  # raced another sweeper, or the file is live
    if removed:
        log.info("swept %d stale partial download(s): %s", len(removed), removed)
    return removed


def download_file(
    url: str,
    dest_path: str,
    progress: bool = True,
    sha256: str | None = None,
    validate: Callable[[str], None] | None = None,
    timeout: float = 60.0,
    retries: int = 3,
    retry_base_delay: float = 0.5,
    stale_part_age_secs: float = 3600.0,
) -> bool:
    """Stream ``url`` into ``dest_path`` atomically; the one download helper
    shared by the Inception tgz fetch and the MNIST idx fetch.

    Writes to a UNIQUE temp file beside the destination (``tempfile.mkstemp``
    — a fixed suffix would let two concurrent processes write through each
    other's fd after the winner's rename), verifies BEFORE the atomic
    ``os.replace`` (``sha256`` hex digest and/or a ``validate(tmp_path)``
    callback that raises on bad content), and never leaves a partial or
    failed file behind to poison later runs' exists-check.

    Transient network errors (OSError family, incl. URLError and the
    ``download`` fault-injection site) are retried ``retries`` times with
    exponential backoff + jitter; verification failures are NOT retried —
    a wrong sha256 stays wrong. Progress goes to **stderr** (stdout belongs
    to scripts that parse it), as percent when the server sends
    Content-Length and as a byte count otherwise.

    Returns True when a download happened, False when ``dest_path`` already
    existed."""
    if os.path.exists(dest_path):
        return False
    dest_dir = os.path.dirname(dest_path) or "."
    ensure_dir_exists(dest_dir)
    name = os.path.basename(dest_path)
    sweep_stale_parts(dest_dir, name, stale_part_age_secs)

    def _attempt() -> None:
        faults.maybe_fail("download", url)
        fd, tmp = tempfile.mkstemp(dir=dest_dir, prefix=name + ".", suffix=".part")
        digest = hashlib.sha256()
        try:
            # Wrap the fd FIRST: urlopen raising before os.fdopen would leak it.
            with os.fdopen(fd, "wb") as f:
                with urllib.request.urlopen(url, timeout=timeout) as r:
                    total = int(r.headers.get("Content-Length") or 0)
                    done = 0
                    while True:
                        chunk = r.read(1 << 16)
                        if not chunk:
                            break
                        f.write(chunk)
                        digest.update(chunk)
                        done += len(chunk)
                        if progress:
                            if total > 0:
                                pct = min(100.0, done / total * 100.0)
                                sys.stderr.write(f"\r>> Downloading {name} {pct:.1f}%")
                            else:
                                sys.stderr.write(
                                    f"\r>> Downloading {name} {done / 1e6:.1f}MB"
                                )
                            sys.stderr.flush()
            if progress:
                sys.stderr.write("\n")
            if sha256 is not None and digest.hexdigest() != sha256.lower():
                raise ValueError(
                    f"{name}: sha256 {digest.hexdigest()} != expected {sha256}"
                )
            if validate is not None:
                validate(tmp)
            # mkstemp creates mode 0600; restore umask-default permissions (what
            # the pre-mkstemp urlretrieve path produced) so a restrictive umask
            # is honored and a permissive one still shares the data_dir.
            os.chmod(tmp, 0o666 & ~_UMASK)
            os.replace(tmp, dest_path)
        except Exception:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    retry_call(
        _attempt,
        attempts=max(1, retries),
        base_delay=retry_base_delay,
        description=f"download {name}",
    )
    log.info("Successfully downloaded %s %d bytes.", name, os.stat(dest_path).st_size)
    return True


def maybe_download_and_extract(
    dest_directory: str,
    url: str = INCEPTION_2015_URL,
    progress: bool = True,
) -> str:
    """Download ``url`` into ``dest_directory`` (skipped when the archive is
    already there) and extract it. Returns the archive path."""
    ensure_dir_exists(dest_directory)
    filename = url.split("/")[-1]
    filepath = os.path.join(dest_directory, filename)
    download_file(url, filepath, progress=progress)
    try:
        with tarfile.open(filepath, "r:gz") as tar:
            # Refuse path traversal and link members (a symlink pointing
            # outside dest would let later members write through it — the
            # name-only realpath check cannot see that).
            base = os.path.realpath(dest_directory)
            for member in tar.getmembers():
                if member.issym() or member.islnk():
                    raise ValueError(f"link member not allowed: {member.name!r}")
                target = os.path.realpath(os.path.join(dest_directory, member.name))
                if not target.startswith(base + os.sep) and target != base:
                    raise ValueError(f"unsafe tar member path: {member.name!r}")
            try:
                tar.extractall(dest_directory, filter="data")
            except TypeError:  # filter= needs >=3.10.12/3.11.4; checks above
                tar.extractall(dest_directory)
    except (tarfile.TarError, OSError, EOFError):
        # A cached-but-corrupt archive (e.g. a captive portal's HTML saved as
        # .tgz) would otherwise cache-hit and fail on every later run.
        os.remove(filepath)
        raise
    return filepath
