"""Model/dataset downloader — parity with the reference's
``maybe_download_and_extract`` (``retrain1/retrain.py:40-62``): fetch a
``.tgz`` with a progress meter if not already present, then extract into the
destination directory. Pure stdlib (urllib + tarfile); works for any URL
scheme urllib supports (https, file:// — the latter is what the offline test
environment uses).
"""

from __future__ import annotations

import os
import sys
import tarfile
import urllib.request

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

# The URL the reference hardcodes (retrain1/retrain.py:27).
INCEPTION_2015_URL = (
    "http://download.tensorflow.org/models/image/imagenet/inception-2015-12-05.tgz"
)


def ensure_dir_exists(dir_name: str) -> None:
    os.makedirs(dir_name, exist_ok=True)


def maybe_download_and_extract(
    dest_directory: str,
    url: str = INCEPTION_2015_URL,
    progress: bool = True,
) -> str:
    """Download ``url`` into ``dest_directory`` (skipped when the archive is
    already there) and extract it. Returns the archive path."""
    ensure_dir_exists(dest_directory)
    filename = url.split("/")[-1]
    filepath = os.path.join(dest_directory, filename)
    if not os.path.exists(filepath):

        def _progress(count, block_size, total_size):
            if not progress or total_size <= 0:
                return
            pct = min(100.0, float(count * block_size) / float(total_size) * 100.0)
            sys.stdout.write(f"\r>> Downloading {filename} {pct:.1f}%")
            sys.stdout.flush()

        try:
            filepath, _ = urllib.request.urlretrieve(url, filepath, _progress)
        except Exception:
            # Leave no partial archive behind — a corrupt .tgz would poison
            # every later run's cache-hit check.
            if os.path.exists(filepath):
                os.remove(filepath)
            raise
        if progress:
            sys.stdout.write("\n")
        log.info(
            "Successfully downloaded %s %d bytes.", filename, os.stat(filepath).st_size
        )
    try:
        with tarfile.open(filepath, "r:gz") as tar:
            # Refuse path traversal and link members (a symlink pointing
            # outside dest would let later members write through it — the
            # name-only realpath check cannot see that).
            base = os.path.realpath(dest_directory)
            for member in tar.getmembers():
                if member.issym() or member.islnk():
                    raise ValueError(f"link member not allowed: {member.name!r}")
                target = os.path.realpath(os.path.join(dest_directory, member.name))
                if not target.startswith(base + os.sep) and target != base:
                    raise ValueError(f"unsafe tar member path: {member.name!r}")
            try:
                tar.extractall(dest_directory, filter="data")
            except TypeError:  # filter= needs >=3.10.12/3.11.4; checks above
                tar.extractall(dest_directory)
    except (tarfile.TarError, OSError, EOFError):
        # A cached-but-corrupt archive (e.g. a captive portal's HTML saved as
        # .tgz) would otherwise cache-hit and fail on every later run.
        os.remove(filepath)
        raise
    return filepath
