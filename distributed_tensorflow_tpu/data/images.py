"""Image-folder dataset: deterministic train/test/validation split.

Parity with the reference's ``create_image_lists`` / ``get_image_path``
(``retrain1/retrain.py:78-128,184-199``): one subfolder per class (jpg/jpeg),
label = folder name lowercased with non-alphanumerics collapsed to spaces,
and a **stable per-file split** decided by SHA-1 of the file's path (with any
``_nohash_`` suffix stripped) mod 2²⁷-1 scaled to a percentage — so a given
image always lands in the same split as the dataset grows.

Faithful quirk kept: the hash covers the full joined path exactly as the
reference computes it (``hash_name = re.sub(r'_nohash_.*$', '', file_name)``
on the glob result, retrain1/retrain.py:111), not just the basename — byte-
for-byte split parity with reference runs on the same ``--image_dir`` string.
"""

from __future__ import annotations

import glob
import hashlib
import os
import re

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

MAX_NUM_IMAGES_PER_CLASS = 2**27 - 1  # retrain1/retrain.py:36
CATEGORIES = ("training", "testing", "validation")
_EXTENSIONS = ("jpg", "jpeg", "JPG", "JPEG")


def split_percentage_hash(file_path: str) -> float:
    """The reference's deterministic split statistic for one file path."""
    hash_name = re.sub(r"_nohash_.*$", "", file_path)
    hashed = hashlib.sha1(hash_name.encode("utf-8")).hexdigest()
    return (int(hashed, 16) % (MAX_NUM_IMAGES_PER_CLASS + 1)) * (
        100.0 / MAX_NUM_IMAGES_PER_CLASS
    )


def create_image_lists(
    image_dir: str, testing_percentage: float, validation_percentage: float
) -> dict | None:
    """→ ``{label: {dir, training: [...], testing: [...], validation: [...]}}``."""
    if not os.path.isdir(image_dir):
        log.error("Image directory '%s' not found.", image_dir)
        return None
    result = {}
    sub_dirs = sorted(
        d for d in os.listdir(image_dir) if os.path.isdir(os.path.join(image_dir, d))
    )
    for dir_name in sub_dirs:
        file_list: list[str] = []
        for extension in _EXTENSIONS:
            file_list.extend(
                glob.glob(os.path.join(image_dir, dir_name, "*." + extension))
            )
        if not file_list:
            log.warning("No files found in '%s'", dir_name)
            continue
        if len(file_list) < 20:
            log.warning(
                "Folder '%s' has less than 20 images, which may cause issues.", dir_name
            )
        elif len(file_list) > MAX_NUM_IMAGES_PER_CLASS:
            log.warning(
                "Folder '%s' has more than %d images; some will never be selected.",
                dir_name,
                MAX_NUM_IMAGES_PER_CLASS,
            )
        label_name = re.sub(r"[^a-z0-9]+", " ", dir_name.lower())
        buckets: dict[str, list[str]] = {c: [] for c in CATEGORIES}
        for file_name in file_list:
            p = split_percentage_hash(file_name)
            if p < validation_percentage:
                buckets["validation"].append(os.path.basename(file_name))
            elif p < testing_percentage + validation_percentage:
                buckets["testing"].append(os.path.basename(file_name))
            else:
                buckets["training"].append(os.path.basename(file_name))
        result[label_name] = {"dir": dir_name, **buckets}
    return result


def get_image_path(
    image_lists: dict, label_name: str, index: int, image_dir: str, category: str
) -> str:
    """Path of the ``index``-th (mod list length) image of a label/category
    (``retrain1/retrain.py:184-199``)."""
    if label_name not in image_lists:
        raise KeyError(f"Label does not exist: {label_name}")
    label_lists = image_lists[label_name]
    if category not in label_lists:
        raise KeyError(f"Category does not exist: {category}")
    category_list = label_lists[category]
    if not category_list:
        raise ValueError(f"Label {label_name} has no images in category {category}")
    base_name = category_list[index % len(category_list)]
    return os.path.join(image_dir, label_lists["dir"], base_name)


def count_images(image_lists: dict, category: str) -> int:
    return sum(len(v[category]) for v in image_lists.values())
