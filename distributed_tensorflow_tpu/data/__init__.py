from distributed_tensorflow_tpu.data.mnist import read_data_sets, DataSet, Datasets  # noqa: F401
