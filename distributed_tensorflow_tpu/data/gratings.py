"""Grating-orientation fixture dataset + generic random-conv features.

The offline accuracy-evidence pair used by the bench harness and the
retrain tests (this environment cannot fetch real MNIST/Inception):

  * :func:`grating_dataset` — horizontal- vs vertical-grating image
    folders with matched per-class pixel statistics (random frequency,
    phase, colors, noise), so unlike a color-blob task a linear model on
    raw pixels is at chance — orientation is carried by spatial structure.
  * :class:`RandomConvExtractor` — a fixed-seed random 5x5 conv bank whose
    bottleneck (per-filter response-energy stats tiled to 2048) makes the
    grating classes linearly separable: the stand-in for transfer from
    generic pretrained features (the real 2015 Inception weights need
    egress; a random-init DEEP Inception's globally-pooled features are
    measured uninformative here — BASELINE.md).
"""

from __future__ import annotations

import os

import numpy as np

from distributed_tensorflow_tpu.data.bottleneck import PathBottleneckMixin


def grating_dataset(
    root: str,
    per_class: int = 40,
    size: int = 64,
    orientations: int = 2,
    noise: float = 12.0,
) -> None:
    """Write one JPEG folder per grating orientation under ``root``.

    ``orientations=2`` (default) keeps the original horizontal/vertical
    folder names; K > 2 writes ``deg0 ... degN`` classes at K angles evenly
    spaced over 180°. More orientations + higher pixel ``noise`` make the
    task HARDER (neighboring angles differ by only 180/K° of spatial
    structure) — the bench uses that to keep its recorded accuracies off
    the 1.0 ceiling, where a metric can no longer show a regression."""
    from PIL import Image

    rng = np.random.default_rng(0)
    angles = np.linspace(0.0, np.pi, orientations, endpoint=False)
    if orientations == 2:
        names = ("horizontal", "vertical")
    else:
        names = tuple(f"deg{int(round(np.degrees(a)))}" for a in angles)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float64)
    for cls, angle in zip(names, angles):
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        # Orientation 0 varies along rows (horizontal stripes), matching the
        # original two-class generator exactly in structure.
        coord = (yy * np.cos(angle) + xx * np.sin(angle)) / size
        for i in range(per_class):
            freq = rng.uniform(2, 6)
            phase = rng.uniform(0, 2 * np.pi)
            wave = 0.5 + 0.5 * np.sin(2 * np.pi * freq * coord + phase)
            img = wave[..., None]
            lo, hi = rng.uniform(0, 80, 3), rng.uniform(150, 255, 3)
            a = lo + img * (hi - lo) + rng.normal(0, noise, (size, size, 3))
            Image.fromarray(np.clip(a, 0, 255).astype(np.uint8)).save(
                os.path.join(d, f"{cls}{i}.jpg")
            )


class RandomConvExtractor(PathBottleneckMixin):
    """Bottleneck extractor drop-in for the retrain pipeline (same duck
    interface as the Inception extractor: ``image_size``, ``bottlenecks``,
    ``bottleneck_for_path`` from the shared mixin)."""

    image_size = 32

    def __init__(self):
        rng = np.random.default_rng(7)
        self.k = (rng.standard_normal((32, 5, 5)) * 0.3).astype(np.float32)

    def bottlenecks(self, imgs):
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(np.asarray(imgs, np.float32).mean(-1) / 255.0)[:, None]
        k = jnp.asarray(self.k)[:, None]  # (32, 1, 5, 5) OIHW
        r = jax.lax.conv_general_dilated(x, k, (1, 1), "VALID")  # (B, 32, h, w)
        feats = jnp.concatenate([jnp.abs(r).mean((2, 3)), r.std((2, 3))], -1)
        reps = 2048 // feats.shape[1] + 1
        return np.asarray(jnp.tile(feats, (1, reps))[:, :2048], np.float32)

