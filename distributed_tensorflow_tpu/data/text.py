"""Byte-level text dataset for LM training.

The reference has no sequence data at all (SURVEY §5.7); this is the
framework-native input path that makes the LM stack trainable on real data:
any file is a token stream at vocab 256 (bytes), no external tokenizer, no
vocabulary files — the right starting point for a framework whose judge is
"can a user actually train on their data".

TPU-first shape discipline: every batch is a fixed (batch, seq_len+0) int32
array sampled as random windows over the stream (training) or as a
sequential non-overlapping sweep (eval), so one compiled step serves the
whole run.
"""

from __future__ import annotations

import numpy as np


def load_byte_tokens(path: str) -> np.ndarray:
    """The whole file as a uint8 token stream (vocab 256)."""
    with open(path, "rb") as fh:
        data = fh.read()
    if not data:
        raise ValueError(f"empty text file: {path}")
    return np.frombuffer(data, dtype=np.uint8)


def encode_text(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), dtype=np.uint8)


def decode_tokens(ids) -> str:
    return bytes(int(t) & 0xFF for t in np.asarray(ids).reshape(-1)).decode(
        "utf-8", errors="replace"
    )


class ByteTextDataset:
    """Random-window training batches + sequential eval sweep over a byte
    stream, with a held-out tail.

    ``holdout_fraction`` of the stream's tail is reserved for eval
    (never sampled by ``train_batch``).
    """

    def __init__(
        self,
        tokens: np.ndarray,
        seq_len: int,
        holdout_fraction: float = 0.05,
        seed: int = 0,
    ):
        tokens = np.asarray(tokens, dtype=np.uint8)
        if not 0 <= holdout_fraction < 1:
            raise ValueError(f"holdout_fraction {holdout_fraction} outside [0, 1)")
        split = int(len(tokens) * (1 - holdout_fraction))
        # Both splits must fit at least one full window.
        if split < seq_len + 1:
            raise ValueError(
                f"text too short: train split {split} tokens < seq_len+1 "
                f"({seq_len + 1})"
            )
        self.seq_len = seq_len
        self.train_tokens = tokens[:split]
        self.eval_tokens = tokens[split:]
        self._seed = seed

    def train_batch(self, batch_size: int, step: int = 0) -> np.ndarray:
        """(batch, seq_len) int32 random windows from the train split.

        Windows are a pure function of ``(seed, step)`` — no mutable rng
        state — so a checkpoint-resumed run at global step N draws exactly
        the windows an uninterrupted run would have drawn at step N."""
        rng = np.random.default_rng((self._seed, step))
        hi = len(self.train_tokens) - self.seq_len
        starts = rng.integers(0, hi + 1, batch_size)
        return np.stack(
            [self.train_tokens[s : s + self.seq_len] for s in starts]
        ).astype(np.int32)

    def eval_batches(self, batch_size: int):
        """Non-overlapping sequential (batch, seq_len) windows over the
        holdout, covering EVERY full window: full batches first, then one
        final smaller batch for the remainder (callers pay at most one extra
        jit compile for that shape). Yields nothing only if the holdout has
        no full window."""
        n_windows = len(self.eval_tokens) // self.seq_len
        windows = self.eval_tokens[: n_windows * self.seq_len].reshape(
            n_windows, self.seq_len
        )
        for lo in range(0, n_windows, batch_size):
            yield windows[lo : lo + batch_size].astype(np.int32)
