"""ImageNet label lookup for the 2015 Inception-v3 1008-way head.

The reference ships (but never parses — its scripts only consume retrained
labels) the two files the 2015 model was distributed with
(``retrain1/inception_model/``):

  * ``imagenet_2012_challenge_label_map_proto.pbtxt`` — text-proto mapping
    the model's int output index (``target_class``) to a WordNet synset UID
    (``target_class_string``, e.g. ``n01440764``);
  * ``imagenet_synset_to_human_label_map.txt`` — tab-separated synset UID →
    human-readable label.

This module composes the two so raw 1008-class logits (e.g. from a GraphDef
imported by ``models.graphdef_import``) print as human labels — the classic
``classify_image.py`` workflow the 2015 bundle was built for.
"""

from __future__ import annotations

import os
import re

LABEL_MAP_PBTXT = "imagenet_2012_challenge_label_map_proto.pbtxt"
SYNSET_TO_HUMAN = "imagenet_synset_to_human_label_map.txt"

_ENTRY_RE = re.compile(
    r"entry\s*\{[^}]*?target_class:\s*(\d+)[^}]*?"
    r'target_class_string:\s*"([^"]+)"[^}]*?\}',
    re.S,
)


def parse_label_map_pbtxt(text: str) -> dict[int, str]:
    """target_class (model output index) → synset UID."""
    return {int(cls): uid for cls, uid in _ENTRY_RE.findall(text)}


def parse_synset_to_human(text: str) -> dict[str, str]:
    """synset UID → human label (first line wins on duplicates)."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        uid, _, human = line.partition("\t")
        out.setdefault(uid.strip(), human.strip())
    return out


class ImagenetLabels:
    """node id → human-readable string (ids without an entry → ``''``)."""

    def __init__(self, node_to_uid: dict[int, str], uid_to_human: dict[str, str]):
        self._node_to_human = {
            node: uid_to_human.get(uid, "") for node, uid in node_to_uid.items()
        }

    @classmethod
    def from_dir(cls, model_dir: str) -> "ImagenetLabels":
        with open(os.path.join(model_dir, LABEL_MAP_PBTXT)) as fh:
            node_to_uid = parse_label_map_pbtxt(fh.read())
        with open(os.path.join(model_dir, SYNSET_TO_HUMAN)) as fh:
            uid_to_human = parse_synset_to_human(fh.read())
        return cls(node_to_uid, uid_to_human)

    def __len__(self) -> int:
        return len(self._node_to_human)

    def name(self, node_id: int) -> str:
        return self._node_to_human.get(int(node_id), "")
