"""Input distortion pipeline (reference C11, ``retrain1/retrain.py:132-165``).

Reference semantics: JPEG decode → random scale (margin = 1 + crop%, times a
uniform resize factor up to 1 + scale%) → bilinear resize → random crop to
299×299 → optional left/right flip → brightness multiply by
uniform(1−b%, 1+b%).

TPU-first redesign: the reference's dynamic-size resize-then-crop cannot be
jitted (XLA needs static shapes). The same transform — scale by ``s`` then
crop a 299² window at a random offset — is expressed as ONE
``jax.image.scale_and_translate`` with static output shape, jitted and
vmapped over the batch with explicit per-example PRNG keys (the reference
relied on TF graph-level randomness). Decode stays on the host (PIL), exactly
as the reference's distorted path feeds decoded tensors
(``retrain1/retrain.py:313-314``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image


def should_distort_images(
    flip_left_right: bool, random_crop: int, random_scale: int, random_brightness: int
) -> bool:
    """Parity with ``retrain1/retrain.py:132-134``: distortions are enabled
    iff any distortion flag is nonzero."""
    return flip_left_right or (random_crop != 0) or (random_scale != 0) or (
        random_brightness != 0
    )


def load_image(path: str, size: int) -> np.ndarray:
    """Host-side decode: RGB uint8 resized to (size, size, 3)."""
    with Image.open(path) as im:
        im = im.convert("RGB").resize((size, size), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


def _distort_one(
    key: jax.Array,
    image: jnp.ndarray,  # (H, W, 3) float32 in [0, 255]
    flip_left_right: bool,
    random_crop: int,
    random_scale: int,
    random_brightness: int,
) -> jnp.ndarray:
    h, w = image.shape[0], image.shape[1]
    k_scale, k_x, k_y, k_flip, k_bright = jax.random.split(key, 5)

    margin_scale = 1.0 + random_crop / 100.0
    resize_scale = 1.0 + jax.random.uniform(k_scale) * (random_scale / 100.0)
    s = margin_scale * resize_scale  # total upscale factor ≥ 1

    # Virtual: resize to (s·h, s·w) then crop (h, w) at uniform offset.
    # Actual: one bilinear resample with static output shape.
    max_off_y = (s - 1.0) * h
    max_off_x = (s - 1.0) * w
    off_y = jax.random.uniform(k_y) * max_off_y
    off_x = jax.random.uniform(k_x) * max_off_x
    out = jax.image.scale_and_translate(
        image,
        shape=(h, w, 3),
        spatial_dims=(0, 1),
        scale=jnp.array([s, s], jnp.float32),
        translation=jnp.array([-off_y, -off_x], jnp.float32),
        method="bilinear",
    )

    if flip_left_right:
        out = jnp.where(jax.random.bernoulli(k_flip), out[:, ::-1, :], out)

    if random_brightness != 0:
        delta = random_brightness / 100.0
        factor = jax.random.uniform(k_bright, minval=1.0 - delta, maxval=1.0 + delta)
        out = out * factor

    return jnp.clip(out, 0.0, 255.0)


@partial(jax.jit, static_argnums=(2, 3, 4, 5))
def distort_batch(
    key: jax.Array,
    images: jnp.ndarray,  # (B, H, W, 3) uint8/float
    flip_left_right: bool = False,
    random_crop: int = 0,
    random_scale: int = 0,
    random_brightness: int = 0,
) -> jnp.ndarray:
    """Vmapped jitted distortion over a batch; returns float32 in [0, 255]."""
    images = jnp.asarray(images, jnp.float32)
    keys = jax.random.split(key, images.shape[0])
    fn = lambda k, im: _distort_one(
        k, im, flip_left_right, random_crop, random_scale, random_brightness
    )
    return jax.vmap(fn)(keys, images)
