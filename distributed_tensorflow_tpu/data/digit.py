"""Hand-drawn digit preprocessing for the demo test CLIs.

Behavioral parity with the reference's ``imageprepare`` (``demo1/test.py:12-42``
== ``demo2/test.py``): grayscale → aspect-preserving resize so the larger
dimension becomes 20 px → SHARPEN filter → paste centered on a white 28×28
canvas (4 px margin on the long side) → invert-normalize so 0=white, 1=black
(matching MNIST's ink-is-high convention).

``Image.ANTIALIAS`` was removed in modern Pillow; ``LANCZOS`` is the same
resampling filter under its current name.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image, ImageFilter

_RESAMPLE = getattr(Image, "LANCZOS", getattr(Image, "Resampling", Image).LANCZOS)


_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif")


def iter_image_files(imgs_dir: str):
    """Yield image-file paths under ``imgs_dir`` (sorted walk, non-image files
    skipped). Shared by every classifier CLI."""
    for root, _, files in os.walk(imgs_dir):
        for fname in sorted(files):
            if fname.lower().endswith(_IMAGE_EXTS):
                yield os.path.join(root, fname)


def show_image(path: str, title: str) -> None:
    import matplotlib.pyplot as plt

    plt.imshow(Image.open(path))
    plt.title(title)
    plt.show()


def classify_digit_images(predict_fn, imgs_dir: str, show: bool = False) -> dict[str, int]:
    """Walk ``imgs_dir``, preprocess each image via :func:`imageprepare`, call
    ``predict_fn((1, 784) array) -> digit``, print and collect results.

    Shared by the demo1/demo2 test CLIs (the reference duplicated this loop
    byte-identically across ``demo1/test.py`` and ``demo2/test.py``).
    Non-image files are skipped instead of crashing the walk."""
    results: dict[str, int] = {}
    for path in iter_image_files(imgs_dir):
        digit = int(predict_fn(imageprepare(path)[None, :]))
        results[path] = digit
        print(f"{path}: the predicted digit is {digit}")
        if show:
            show_image(path, f"predicted: {digit}")
    if not results:
        print(f"no images found under {imgs_dir}")
    return results


def imageprepare(path: str) -> np.ndarray:
    """Load an image file → flat float32 (784,) in [0,1], MNIST-style."""
    im = Image.open(path).convert("L")
    width, height = float(im.size[0]), float(im.size[1])
    canvas = Image.new("L", (28, 28), 255)
    if width > height:
        nheight = max(1, int(round(20.0 / width * height)))
        img = im.resize((20, nheight), _RESAMPLE).filter(ImageFilter.SHARPEN)
        wtop = int(round((28 - nheight) / 2))
        canvas.paste(img, (4, wtop))
    else:
        nwidth = max(1, int(round(20.0 / height * width)))
        img = im.resize((nwidth, 20), _RESAMPLE).filter(ImageFilter.SHARPEN)
        wleft = int(round((28 - nwidth) / 2))
        canvas.paste(img, (wleft, 4))
    arr = np.asarray(canvas, dtype=np.float32).reshape(-1)
    return (255.0 - arr) / 255.0
