"""MNIST idx loader — in-repo replacement for the TF tutorial ``input_data``
module the reference imports (``demo1/train.py:6``; ``demo2/train.py:8``).

Parses idx ``.gz`` files directly with numpy (the reference delegated this to
``tensorflow.examples.tutorials.mnist``). API parity:

    mnist = read_data_sets("MNIST_data", one_hot=True)
    xs, ys = mnist.train.next_batch(100)        # demo1/train.py:154
    mnist.test.images, mnist.test.labels        # demo1/train.py:159

``next_batch`` keeps the tutorial semantics: shuffle once per epoch, then
serve sequential slices. Data sources, in order of realism:

* **Real digits, bundled**: the repo ships the genuine public MNIST t10k
  idx files (10,000 digits; ``demo1/MNIST_data/``, mirrored from the
  reference checkout, whose 60k train-images file is absent —
  ``.MISSING_LARGE_BLOBS``). ``t10k_split=k`` trains on ``10000-k`` of
  them and holds out ``k`` for eval (:func:`read_data_sets`), so real-data
  accuracy is measurable offline; the ceiling is 10k examples, not 60k.
* **Download-if-absent** (``download=True``): the reference's auto-fetch
  behavior — needs network egress.
* **Synthetic** (``synthetic=True``): deterministic learnable stand-in
  with identical shapes/dtypes, for tests and egress-less throughput work.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
TEST_LABELS = "t10k-labels-idx1-ubyte.gz"
ALL_FILES = (TRAIN_IMAGES, TRAIN_LABELS, TEST_IMAGES, TEST_LABELS)

# Where the TF tutorial loader the reference imports fetched from
# (``input_data.read_data_sets`` auto-download, demo1/train.py:6).
MNIST_BASE_URL = "https://storage.googleapis.com/cvdf-datasets/mnist/"

_IDX_IMAGE_MAGIC = 2051
_IDX_LABEL_MAGIC = 2049

# The t10k train/holdout split must not move with the training seed: a fixed
# split seed keeps the holdout identical across runs, so accuracies stay
# comparable (and a --seed sweep can't leak holdout digits into training).
_T10K_SPLIT_SEED = 2026


def bundled_mnist_dir() -> str | None:
    """Directory of the repo-bundled REAL MNIST t10k idx files (public
    dataset, mirrored from the reference checkout at
    ``/root/reference/demo1/MNIST_data``), or None when absent (e.g. an
    installed package without the repo tree). The bundle also mirrors the
    genuine 60k ``train-labels`` file: unused by ``t10k_split`` itself, but
    with it in place a single ``--download_data`` fetch of the one absent
    file (``train-images``) completes the full dataset."""
    d = os.path.normpath(
        os.path.join(os.path.dirname(__file__), os.pardir, os.pardir, "demo1", "MNIST_data")
    )
    if all(os.path.exists(os.path.join(d, n)) for n in (TEST_IMAGES, TEST_LABELS)):
        return d
    return None


def _open_maybe_gz(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    """Parse an idx3-ubyte (optionally gzipped) image file → (N, rows*cols) float32 in [0,1]."""
    with _open_maybe_gz(path) as fh:
        magic, n, rows, cols = struct.unpack(">IIII", fh.read(16))
        if magic != _IDX_IMAGE_MAGIC:
            raise ValueError(f"{path}: bad idx image magic {magic}")
        buf = fh.read(n * rows * cols)
    arr = np.frombuffer(buf, dtype=np.uint8).reshape(n, rows * cols)
    return arr.astype(np.float32) / 255.0


def read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as fh:
        magic, n = struct.unpack(">II", fh.read(8))
        if magic != _IDX_LABEL_MAGIC:
            raise ValueError(f"{path}: bad idx label magic {magic}")
        buf = fh.read(n)
    return np.frombuffer(buf, dtype=np.uint8).copy()


def write_idx_images(path: str, images_u8: np.ndarray) -> None:
    """Write (N, rows, cols) uint8 images as idx3-ubyte.gz (test fixtures)."""
    n, rows, cols = images_u8.shape
    with gzip.open(path, "wb") as fh:
        fh.write(struct.pack(">IIII", _IDX_IMAGE_MAGIC, n, rows, cols))
        fh.write(images_u8.astype(np.uint8).tobytes())


def write_idx_labels(path: str, labels_u8: np.ndarray) -> None:
    with gzip.open(path, "wb") as fh:
        fh.write(struct.pack(">II", _IDX_LABEL_MAGIC, labels_u8.shape[0]))
        fh.write(labels_u8.astype(np.uint8).tobytes())


def _validate_idx_gz(path: str) -> None:
    """Structural integrity check of a downloaded idx ``.gz``: gzip framing,
    idx magic, and exact payload length for the declared dims. This is the
    offline-verifiable stand-in for a pinned checksum (the canonical hashes
    cannot be confirmed from this egress-less environment; callers that have
    them can pass ``checksums=`` to :func:`maybe_download_mnist`)."""
    with gzip.open(path, "rb") as fh:
        (magic,) = struct.unpack(">I", fh.read(4))
        if magic == _IDX_IMAGE_MAGIC:
            n, rows, cols = struct.unpack(">III", fh.read(12))
            expect = n * rows * cols
        elif magic == _IDX_LABEL_MAGIC:
            (expect,) = struct.unpack(">I", fh.read(4))
        else:
            raise ValueError(f"{path}: bad idx magic {magic}")
        got = 0
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            got += len(chunk)
        if got != expect:
            raise ValueError(f"{path}: idx payload {got} bytes, header says {expect}")


def maybe_download_mnist(
    data_dir: str,
    base_url: str = MNIST_BASE_URL,
    progress: bool = True,
    checksums: dict[str, str] | None = None,
    timeout: float = 60.0,
    files: tuple[str, ...] = ALL_FILES,
) -> list[str]:
    """Fetch any missing MNIST idx ``.gz`` into ``data_dir`` — the
    reference's download-if-absent behavior (``input_data.read_data_sets``,
    ``demo1/train.py:6``) on the shared hardened fetcher
    (:func:`data.download.download_file`: unique temp file, verification
    BEFORE the atomic rename, no partial/corrupt leftovers). Verification =
    structural idx check (:func:`_validate_idx_gz`) plus ``checksums[name]``
    = hex sha256 when provided.

    Returns the file names actually fetched (empty when all were present).
    """
    from distributed_tensorflow_tpu.data.download import download_file

    fetched: list[str] = []
    for name in files:
        if download_file(
            base_url.rstrip("/") + "/" + name,
            os.path.join(data_dir, name),
            progress=progress,
            sha256=(checksums or {}).get(name),
            validate=_validate_idx_gz,
            timeout=timeout,
        ):
            fetched.append(name)
    return fetched


def one_hot(labels: np.ndarray, num_classes: int = 10) -> np.ndarray:
    out = np.zeros((labels.shape[0], num_classes), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


_one_hot = one_hot  # module-level alias (read_data_sets has a `one_hot` kwarg)


class DataSet:
    """Epoch-shuffled sequential minibatch iterator (tutorial ``next_batch`` parity)."""

    def __init__(self, images: np.ndarray, labels: np.ndarray, seed: int = 0):
        assert images.shape[0] == labels.shape[0]
        self.images = images
        self.labels = labels
        self._num_examples = images.shape[0]
        self._rng = np.random.default_rng(seed)
        self._index = 0
        self._order = self._rng.permutation(self._num_examples)

    @property
    def num_examples(self) -> int:
        return self._num_examples

    def reseed_shuffle(self, seed: int) -> None:
        """Restart the shuffle stream (dataset content untouched) — used to
        decorrelate per-process sampling in multi-worker training while every
        process still holds identical data."""
        self._rng = np.random.default_rng(seed)
        self._order = self._rng.permutation(self._num_examples)
        self._index = 0

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        if self._index + batch_size > self._num_examples:
            self._order = self._rng.permutation(self._num_examples)
            self._index = 0
        idx = self._order[self._index : self._index + batch_size]
        self._index += batch_size
        return self.images[idx], self.labels[idx]


class Datasets:
    def __init__(self, train: DataSet, test: DataSet, validation: DataSet | None = None):
        self.train = train
        self.test = test
        self.validation = validation


def synthetic_mnist(
    num_train: int = 5000,
    num_test: int = 1000,
    seed: int = 0,
    noise: float = 0.25,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic learnable stand-in for MNIST: each class is a fixed random
    28×28 blob pattern; samples are the class template blended with ``noise``
    fraction of uniform noise (0.25 = easily saturated; ~0.5 keeps accuracy
    off the 1.0 ceiling so the bench metric can show regressions).
    Shapes/dtypes identical to the real dataset."""
    rng = np.random.default_rng(seed)
    templates = rng.random((10, 784)).astype(np.float32)
    # Smooth the templates a little so conv features are meaningful.
    t = templates.reshape(10, 28, 28)
    t = (t + np.roll(t, 1, 1) + np.roll(t, 1, 2) + np.roll(t, -1, 1) + np.roll(t, -1, 2)) / 5.0
    templates = t.reshape(10, 784)

    def make(n, rng):
        labels = rng.integers(0, 10, size=n).astype(np.uint8)
        u = rng.random((n, 784)).astype(np.float32)
        images = np.clip((1.0 - noise) * templates[labels] + noise * u, 0.0, 1.0)
        return images, labels

    xi, yi = make(num_train, np.random.default_rng(seed + 1))
    xt, yt = make(num_test, np.random.default_rng(seed + 2))
    return xi, yi, xt, yt


def read_data_sets(
    data_dir: str,
    one_hot: bool = True,
    seed: int = 0,
    synthetic: bool = False,
    num_synthetic_train: int = 5000,
    num_synthetic_test: int = 1000,
    synthetic_noise: float = 0.25,
    download: bool = False,
    base_url: str = MNIST_BASE_URL,
    t10k_split: int = 0,
) -> Datasets:
    """Load MNIST from idx files in ``data_dir``. When files are absent:
    ``download=True`` first tries :func:`maybe_download_mnist` (the
    reference's auto-fetch, ``demo1/train.py:6``); then ``synthetic=True``
    falls back to the deterministic synthetic dataset. Both unset → a clear
    error.

    ``t10k_split=k`` (with k > 0) is the REAL-data mode for checkouts where
    only the t10k files exist (the reference checkout is missing the 60k
    train-images blob): it loads the 10,000 genuine test digits and splits
    them into ``10000-k`` training examples and a ``k``-digit holdout. The
    split is a fixed permutation (``_T10K_SPLIT_SEED``), independent of
    ``seed``, so the holdout never moves between runs. Mutually exclusive
    with ``synthetic``."""
    if t10k_split:
        if synthetic:
            raise ValueError("t10k_split and synthetic are mutually exclusive")
        ip = os.path.join(data_dir, TEST_IMAGES)
        lp = os.path.join(data_dir, TEST_LABELS)
        missing = [p for p in (ip, lp) if not os.path.exists(p)]
        if missing and download:
            maybe_download_mnist(
                data_dir, base_url=base_url, files=(TEST_IMAGES, TEST_LABELS)
            )
            missing = [p for p in (ip, lp) if not os.path.exists(p)]
        if missing:
            hint = bundled_mnist_dir()
            raise FileNotFoundError(
                f"t10k_split needs the real t10k idx files; missing: {missing}."
                + (f" Bundled copies exist at {hint}." if hint else "")
            )
        x, y = read_idx_images(ip), read_idx_labels(lp)
        n = x.shape[0]
        if not 0 < t10k_split < n:
            raise ValueError(f"t10k_split must be in (0, {n}), got {t10k_split}")
        perm = np.random.default_rng(_T10K_SPLIT_SEED).permutation(n)
        tr, ho = perm[: n - t10k_split], perm[n - t10k_split :]
        train_yy = _one_hot(y[tr]) if one_hot else y[tr]
        test_yy = _one_hot(y[ho]) if one_hot else y[ho]
        return Datasets(
            train=DataSet(x[tr], train_yy, seed=seed),
            test=DataSet(x[ho], test_yy, seed=seed + 1),
        )
    paths = {k: os.path.join(data_dir, k) for k in ALL_FILES}
    have_all = all(os.path.exists(p) for p in paths.values())
    if not have_all and download:
        try:
            maybe_download_mnist(data_dir, base_url=base_url)
            have_all = True
        except Exception as e:
            if not synthetic:
                raise
            from distributed_tensorflow_tpu.utils.logging import get_logger

            get_logger(__name__).warning(
                "MNIST download failed (%s); using the synthetic fallback.", e
            )
    if have_all:
        train_x = read_idx_images(paths[TRAIN_IMAGES])
        train_y = read_idx_labels(paths[TRAIN_LABELS])
        test_x = read_idx_images(paths[TEST_IMAGES])
        test_y = read_idx_labels(paths[TEST_LABELS])
    elif synthetic:
        train_x, train_y, test_x, test_y = synthetic_mnist(
            num_synthetic_train, num_synthetic_test, seed, noise=synthetic_noise
        )
    else:
        missing = [k for k, p in paths.items() if not os.path.exists(p)]
        raise FileNotFoundError(
            f"MNIST idx files missing in {data_dir}: {missing}. "
            "No network egress is available; pass synthetic=True (or --synthetic_data) "
            "for a deterministic stand-in dataset."
        )
    if one_hot:
        train_yy, test_yy = _one_hot(train_y), _one_hot(test_y)
    else:
        train_yy, test_yy = train_y, test_y
    return Datasets(
        train=DataSet(train_x, train_yy, seed=seed),
        test=DataSet(test_x, test_yy, seed=seed + 1),
    )
