"""Asynchronous host→device input prefetching.

The reference's hot loop pays a host round-trip every step: ``feed_dict``
re-uploads the batch inside ``sess.run`` (``demo1/train.py:153-155``), and in
the distributed case the worker additionally pulls variables from the ps over
gRPC (``demo2/train.py:183``). On TPU the equivalent stall is the host-side
``next_batch`` + ``device_put`` sitting serially in front of each dispatched
step, leaving the chip idle while Python slices numpy arrays.

:class:`Prefetcher` moves that host work onto a background thread with a small
bounded queue: batch assembly and the HBM transfer for step *k+depth* overlap
the device computation of step *k*. Because JAX dispatch is already
asynchronous, a queue depth of 2 is enough to keep the TPU busy; deeper queues
only add HBM pressure (each queued batch is resident on device).

Consumer starvation is MEASURED here, not inferred: ``__next__`` times how
long it blocks on the queue and records it (plus the queue depth it found)
into the obs registry — ``data_wait_seconds_total`` is the exact data-wait
slice of the train loop's step-time decomposition. An empty queue at dequeue
means the input pipeline, not the device, is the bottleneck.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from distributed_tensorflow_tpu import obs

__all__ = [
    "Prefetcher",
    "batches_forever",
    "bounded_device_batches",
    "stacked_device_batches",
]

_SENTINEL = object()


class Prefetcher:
    """Iterate ``place_fn(item)`` for items of ``source``, computed ``depth``
    batches ahead on a daemon thread.

    ``source``    — iterable yielding host-side batches (may be infinite).
    ``place_fn``  — host→device placement, e.g. ``lambda b: shard_batch(b, mesh)``;
                    runs on the worker thread so the transfer overlaps compute.
    ``depth``     — max device-resident batches queued ahead (≥1).
    ``registry``  — obs metrics registry to record starvation into (defaults
                    to the process registry; pass a private one in tests).

    ``starvation_seconds`` accumulates the total time the CONSUMER spent
    blocked in ``__next__`` waiting for a batch — the host-input slice of
    step time. The same quantity goes into the registry's
    ``data_wait_seconds_total`` counter, and the queue depth found at each
    dequeue into the ``data_queue_depth`` histogram.

    Exceptions raised by ``source``/``place_fn`` propagate to the consumer at
    the next ``__next__``. Use as a context manager (or call :meth:`close`) to
    stop the worker before the source is exhausted.
    """

    def __init__(
        self,
        source: Iterable[Any],
        place_fn: Callable[[Any], Any] | None = None,
        depth: int = 2,
        registry=None,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._place = place_fn if place_fn is not None else (lambda x: x)
        reg = registry if registry is not None else obs.get_registry()
        self._wait_total = reg.counter(
            "data_wait_seconds_total",
            "Seconds the training thread blocked waiting for input batches.")
        self._depth_hist = reg.histogram(
            "data_queue_depth",
            "Prefetch queue depth found at each dequeue (0 = starved).",
            buckets=(0.0, 1.0, 2.0, 4.0, 8.0))
        self.starvation_seconds = 0.0
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._worker, args=(iter(source),), daemon=True, name="input-prefetch"
        )
        self._thread.start()

    def _worker(self, it: Iterator[Any]) -> None:
        try:
            for item in it:
                placed = self._place(item)
                while not self._stop.is_set():
                    try:
                        self._q.put(placed, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — propagated to consumer
            self._error = e
        # Exhausted (or errored): wake the consumer.
        while not self._stop.is_set():
            try:
                self._q.put(_SENTINEL, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> Any:
        if self._done:  # sentinel is enqueued once; don't block on a drained queue
            raise StopIteration
        self._depth_hist.observe(float(self._q.qsize()))
        try:
            # Fast path: batch already staged — zero measured wait.
            item = self._q.get_nowait()
        except queue.Empty:
            t0 = time.perf_counter()
            item = self._q.get()
            waited = time.perf_counter() - t0
            self.starvation_seconds += waited
            self._wait_total.inc(waited)
        if item is _SENTINEL:
            self._done = True
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the worker thread and drop queued batches."""
        self._done = True  # later __next__ raises StopIteration, never blocks
        self._stop.set()
        # Drain so a blocked put() notices the stop flag quickly.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def batches_forever(dataset, batch_size: int) -> Iterator[dict]:
    """Infinite ``{'image', 'label'}`` batch generator over a
    :class:`~distributed_tensorflow_tpu.data.mnist.DataSet` (epoch-shuffled
    ``next_batch`` semantics, ``demo1/train.py:154``)."""
    while True:
        xs, ys = dataset.next_batch(batch_size)
        yield {"image": xs, "label": ys}


def bounded_device_batches(dataset, batch_size: int, mesh, num_batches: int, depth: int = 2) -> Prefetcher:
    """The standard training input pipeline: exactly ``num_batches`` batches
    from ``dataset``, sharded onto ``mesh`` on a background thread. Bounding
    the source (rather than closing an infinite one) guarantees the lookahead
    never pulls batches that get discarded, so a segmented run — train(100)
    then train(200) after restore — consumes the identical example stream as
    one uninterrupted run."""
    import itertools

    from distributed_tensorflow_tpu.parallel import data_parallel as dp

    return Prefetcher(
        itertools.islice(batches_forever(dataset, batch_size), num_batches),
        place_fn=lambda b: dp.shard_batch(b, mesh),
        depth=depth,
    )


def stacked_device_batches(
    dataset, batch_size: int, mesh, chunk_sizes: list[int], depth: int = 2
) -> Prefetcher:
    """Input pipeline for :func:`~..parallel.data_parallel.build_multi_step`:
    for each k in ``chunk_sizes``, assemble k consecutive batches and place
    them as one ``(k, B, ...)`` stacked device batch. The underlying example
    stream is identical to ``bounded_device_batches`` with
    ``sum(chunk_sizes)`` batches — fusion changes dispatch, not data order."""
    from distributed_tensorflow_tpu.parallel import data_parallel as dp

    source = batches_forever(dataset, batch_size)

    def chunks():
        for k in chunk_sizes:
            yield [next(source) for _ in range(k)]

    return Prefetcher(
        chunks(),
        place_fn=lambda bs: dp.stack_shard_batches(bs, mesh),
        depth=depth,
    )
