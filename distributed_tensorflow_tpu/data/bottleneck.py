"""Bottleneck feature cache (reference C12, ``retrain1/retrain.py:168-245,
300-369``).

Each image is pushed through Inception-v3 to its 2048-d penultimate
("bottleneck") vector and cached on disk as comma-separated floats at
``bottleneck_dir/<label>/<image>.txt`` — same path scheme and text codec as
the reference, including corruption recovery (a cache file that fails to
parse is regenerated, ``retrain1/retrain.py:212-224``).

TPU-first divergence: the reference ran one ``sess.run`` per image
(``retrain1/retrain.py:229``); here featurization is **batched** through one
jitted apply — images are decoded host-side, stacked, and pushed through the
MXU hundreds at a time.

Batch samplers (``retrain1/retrain.py:300-354``):
  * ``how_many >= 0`` → sample with replacement (uniform label, uniform index)
  * ``how_many == -1`` → deterministic full sweep of a category
  * distorted variant bypasses the cache and re-featurizes every time
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import _native
from distributed_tensorflow_tpu.data import images as I
from distributed_tensorflow_tpu.data.augment import distort_batch, load_image
from distributed_tensorflow_tpu.models import inception_v3 as iv3
from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


class PathBottleneckMixin:
    """The one path→bottleneck contract shared by every extractor (the
    Inception runner here, the random-conv fixture in ``data/gratings.py``,
    test fakes): load at ``self.image_size``, run ``self.bottlenecks``."""

    def bottleneck_for_path(self, path: str) -> np.ndarray:
        return self.bottlenecks(load_image(path, self.image_size)[None])[0]


class FeatureExtractor(PathBottleneckMixin):
    """Jitted batched Inception-v3 bottleneck runner."""

    def __init__(self, model: iv3.InceptionV3, variables, image_size: int = iv3.INPUT_SIZE):
        self.model = model
        self.variables = variables
        self.image_size = image_size
        self._apply = jax.jit(
            lambda v, x: model.apply(v, iv3.preprocess(x), return_bottleneck=True)
        )

    def bottlenecks(self, images_u8: np.ndarray) -> np.ndarray:
        """(B, H, W, 3) uint8/float [0,255] → (B, 2048) float32."""
        return np.asarray(self._apply(self.variables, jnp.asarray(images_u8)))

# ---------------------------------------------------------------------------
# Cache codec (text, comma-separated — reference parity).
# ---------------------------------------------------------------------------


def get_bottleneck_path(
    image_lists: dict, label_name: str, index: int, bottleneck_dir: str, category: str
) -> str:
    """``retrain1/retrain.py:202-204``: image path under bottleneck_dir + '.txt'."""
    return I.get_image_path(image_lists, label_name, index, bottleneck_dir, category) + ".txt"


def write_bottleneck_file(
    path: str, values: np.ndarray, expected_size: int = iv3.BOTTLENECK_SIZE
) -> np.ndarray:
    """Atomic write (tmp + os.replace): concurrent workers in a shared
    bottleneck_dir (retrain2) must never expose a torn file to a reader.

    Validates the vector length up front (a wrong-size write would otherwise
    poison the cache: every later read warns and regenerates forever) and
    returns the **text-codec roundtrip** of ``values`` so a cache-miss caller
    can return exactly what every cache-hit read will return — cold- and
    warm-cache runs then consume bit-identical training inputs."""
    values = np.asarray(values, dtype=np.float32).reshape(-1)
    if expected_size and values.shape != (expected_size,):
        raise ValueError(
            f"refusing to write {path}: expected {expected_size} floats, got {values.shape}"
        )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # Native codec (shortest-round-trip float32 decimals, C++ to_chars) when
    # available; Python repr fallback. Both parse back to identical float32s
    # from either reader, so mixed native/fallback processes share a cache.
    data = _native.format_csv_floats(values)
    if data is None:
        data = ",".join(str(float(x)) for x in values).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
    os.replace(tmp, path)
    return _parse_csv(data)


def _parse_csv(data: bytes, expected_size: int | None = None) -> np.ndarray:
    """Parse the cache text format; raises ValueError on corruption."""
    parsed = _native.parse_csv_floats(data, expected_size)
    if parsed is not None:
        return parsed
    return np.array([float(x) for x in data.split(b",")], dtype=np.float32)


def read_bottleneck_file(path: str, expected_size: int = iv3.BOTTLENECK_SIZE) -> np.ndarray:
    """Raises ValueError on corruption (caller regenerates) — including a
    cleanly-truncated file whose floats all parse but whose length is wrong."""
    with open(path, "rb") as fh:
        values = _parse_csv(fh.read(), expected_size or None)
    if expected_size and values.shape != (expected_size,):
        raise ValueError(f"{path}: expected {expected_size} floats, got {values.shape}")
    return values


def get_or_create_bottleneck(
    extractor: FeatureExtractor,
    image_lists: dict,
    label_name: str,
    index: int,
    image_dir: str,
    category: str,
    bottleneck_dir: str,
) -> np.ndarray:
    """Cache-hit read with regenerate-on-corruption (``retrain1/retrain.py:206-232``)."""
    bpath = get_bottleneck_path(image_lists, label_name, index, bottleneck_dir, category)
    if os.path.exists(bpath):
        try:
            return read_bottleneck_file(bpath)
        except ValueError:
            log.warning("invalid bottleneck file %s — regenerating", bpath)
    ipath = I.get_image_path(image_lists, label_name, index, image_dir, category)
    values = extractor.bottleneck_for_path(ipath)
    return write_bottleneck_file(bpath, values)


def cache_bottlenecks(
    extractor: FeatureExtractor,
    image_lists: dict,
    image_dir: str,
    bottleneck_dir: str,
    batch_size: int = 64,
) -> int:
    """Precompute every missing bottleneck, batched through the TPU (the
    reference looped one sess.run per image, ``retrain1/retrain.py:168-180``).
    Returns the number of bottlenecks newly created."""
    os.makedirs(bottleneck_dir, exist_ok=True)
    todo: list[tuple[str, str]] = []  # (image path, bottleneck path)
    for label_name, label_lists in image_lists.items():
        for category in I.CATEGORIES:
            for index in range(len(label_lists[category])):
                bpath = get_bottleneck_path(
                    image_lists, label_name, index, bottleneck_dir, category
                )
                if os.path.exists(bpath):
                    try:
                        read_bottleneck_file(bpath)
                        continue
                    except ValueError:
                        log.warning("invalid bottleneck file %s — regenerating", bpath)
                todo.append(
                    (I.get_image_path(image_lists, label_name, index, image_dir, category), bpath)
                )
    created = 0
    for lo in range(0, len(todo), batch_size):
        chunk = todo[lo : lo + batch_size]
        imgs = np.stack([load_image(p, extractor.image_size) for p, _ in chunk])
        vecs = extractor.bottlenecks(imgs)
        for (_, bpath), vec in zip(chunk, vecs):
            write_bottleneck_file(bpath, vec)
        created += len(chunk)
        if created and created % 100 < batch_size:
            log.info("%d bottleneck files created.", created)
    return created


# ---------------------------------------------------------------------------
# Batch samplers.
# ---------------------------------------------------------------------------


def get_random_cached_bottlenecks(
    extractor: FeatureExtractor,
    image_lists: dict,
    how_many: int,
    category: str,
    bottleneck_dir: str,
    image_dir: str,
    rng: np.random.Generator,
    memo: dict | None = None,
):
    """→ (bottlenecks (N,2048), one-hot truths (N,K), filenames). Sampling
    parity with ``retrain1/retrain.py:318-341``: uniform over labels, uniform
    index with replacement; ``how_many == -1`` sweeps every image.

    ``memo`` (path → vector) is an optional in-memory layer over the disk
    cache: the reference re-read and re-parsed cache files every step — its
    hot loop was disk-bound (SURVEY §7d). First access still goes through
    disk (corruption recovery preserved); each vector is then served from
    memory (2048 floats = 8 KB/image)."""
    label_names = list(image_lists.keys())
    pairs = _sample_index_pairs(image_lists, how_many, category, rng)
    bottlenecks, truths, filenames = [], [], []
    for label_index, image_index in pairs:
        label_name = label_names[label_index]
        ipath = I.get_image_path(image_lists, label_name, image_index, image_dir, category)
        if memo is not None and ipath in memo:
            vec = memo[ipath]
        else:
            vec = get_or_create_bottleneck(
                extractor, image_lists, label_name, image_index, image_dir, category, bottleneck_dir
            )
            if memo is not None:
                memo[ipath] = vec
        bottlenecks.append(vec)
        truths.append(_one_hot(len(label_names), label_index))
        filenames.append(ipath)
    return np.stack(bottlenecks), np.stack(truths), filenames


def _one_hot(class_count: int, label_index: int) -> np.ndarray:
    truth = np.zeros(class_count, np.float32)
    truth[label_index] = 1.0
    return truth


def _sample_index_pairs(
    image_lists: dict, how_many: int, category: str, rng: np.random.Generator
) -> list[tuple[int, int]]:
    """Shared sampling policy → list of (label_index, image_index).

    ``how_many >= 0``: with replacement, uniform label then uniform index mod
    list length (``retrain1/retrain.py:322-326``). Robustness divergence: the
    reference fataled when the sampled label had no images in the category
    (retrain1/retrain.py:192) — possible for small classes since the SHA-1
    split gives no per-class guarantees; sample only from labels that do.
    ``how_many == -1``: deterministic full sweep (``retrain1/retrain.py:333-341``).
    """
    label_names = list(image_lists.keys())
    if how_many >= 0:
        eligible = [i for i, n in enumerate(label_names) if image_lists[n][category]]
        if not eligible:
            raise ValueError(f"no label has any images in category {category}")
        return [
            (
                eligible[int(rng.integers(len(eligible)))],
                int(rng.integers(I.MAX_NUM_IMAGES_PER_CLASS + 1)),
            )
            for _ in range(how_many)
        ]
    return [
        (label_index, image_index)
        for label_index, label_name in enumerate(label_names)
        for image_index in range(len(image_lists[label_name][category]))
    ]


def get_random_distorted_bottlenecks(
    extractor: FeatureExtractor,
    image_lists: dict,
    how_many: int,
    category: str,
    image_dir: str,
    rng: np.random.Generator,
    distort_key: jax.Array,
    flip_left_right: bool = False,
    random_crop: int = 0,
    random_scale: int = 0,
    random_brightness: int = 0,
):
    """Distorted sampler (``retrain1/retrain.py:344-354``): bypasses the
    cache — images are re-decoded, jit-distorted, and re-featurized each call,
    batched (the reference ran two sess.runs per image)."""
    label_names = list(image_lists.keys())
    imgs, truths = [], []
    for label_index, image_index in _sample_index_pairs(image_lists, how_many, category, rng):
        path = I.get_image_path(
            image_lists, label_names[label_index], image_index, image_dir, category
        )
        imgs.append(load_image(path, extractor.image_size))
        truths.append(_one_hot(len(label_names), label_index))
    batch = distort_batch(
        distort_key,
        np.stack(imgs),
        flip_left_right,
        random_crop,
        random_scale,
        random_brightness,
    )
    return extractor.bottlenecks(np.asarray(batch)), np.stack(truths)
