"""Transfer-learning trainer — the retrain1/retrain2 workload, TPU-native
(reference C15/C16: ``retrain1/retrain.py:372-476``,
``retrain2/retrain2.py:366-508``).

Pipeline parity:
  1. wipe + recreate the summaries dir (``:374-376``)
  2. build the Inception-v3 feature extractor (frozen trunk; the reference
     downloaded+imported a frozen GraphDef — here weights load from
     ``--model_dir`` if a converted bundle exists, else random init)
  3. ``create_image_lists`` deterministic split; abort on <2 classes
     (``:388-394``)
  4. cache all bottlenecks up front on the non-distorted path (``:417-418``),
     batched through the TPU
  5. per step: sample a train batch (cached or freshly-distorted), one
     gradient-descent step on the head; every ``eval_step_interval`` evaluate
     a validation batch (``:424-457``)
  6. final full test-set eval, optional misclassified-image listing (the
     reference parsed ``--print_misclassified_test_images`` but never used
     it — implemented here), export params bundle + labels file (``:459-475``)

Distributed (retrain2) divergences, both documented improvements: head
training is synchronous SPMD over the mesh instead of async PS; bottleneck
caching is **sharded across processes** by index stride instead of every
worker duplicating the full cache pass (``retrain2/retrain2.py:437-438``).
"""

from __future__ import annotations

import os
import shutil
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.config import RetrainConfig
from distributed_tensorflow_tpu.data import bottleneck as B
from distributed_tensorflow_tpu.data import images as I
from distributed_tensorflow_tpu.data.augment import should_distort_images
from distributed_tensorflow_tpu.models import inception_v3 as iv3
from distributed_tensorflow_tpu.models.head import BottleneckHead
from distributed_tensorflow_tpu.parallel import data_parallel as dp
from distributed_tensorflow_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_tpu.train import resilience
from distributed_tensorflow_tpu.train.checkpoint import export_inference_bundle
from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.logging import get_logger
from distributed_tensorflow_tpu.utils.summary import SummaryWriter
from distributed_tensorflow_tpu.utils.timer import WallClock

log = get_logger(__name__)


def build_extractor(cfg: RetrainConfig, image_size: int = iv3.INPUT_SIZE):
    """Feature extractor with weights from ``--model_dir``: the reference's
    own ``classify_image_graph_def.pb`` (read TF-free by
    ``models.graphdef_import`` — full parity with ``retrain1/retrain.py:66-74``),
    a converted bundle (``inception_v3.msgpack`` / ``.npz``), or random init
    when neither is present (this environment cannot download — no egress)."""
    model = iv3.create_model()
    pb_path = os.path.join(cfg.model_dir, "classify_image_graph_def.pb")
    if not os.path.exists(pb_path) and getattr(cfg, "model_download_url", ""):
        from distributed_tensorflow_tpu.data.download import maybe_download_and_extract

        maybe_download_and_extract(cfg.model_dir, url=cfg.model_download_url)
    if os.path.exists(pb_path):
        from distributed_tensorflow_tpu.models.graphdef_import import (
            import_inception_graphdef,
        )

        log.info("importing frozen GraphDef weights from %s", pb_path)
        variables, report = import_inception_graphdef(pb_path, model=model)
        log.info(
            "GraphDef import: %d tensors loaded, %d defaulted",
            len(report["loaded"]), len(report["defaulted"]),
        )
        return B.FeatureExtractor(model, variables, image_size)
    for name in ("inception_v3.msgpack", "inception_v3.npz"):
        path = os.path.join(cfg.model_dir, name)
        if os.path.exists(path):
            log.info("loading Inception-v3 weights from %s", path)
            variables = iv3.load_pretrained(path, model, image_size=image_size)
            return B.FeatureExtractor(model, variables, image_size)
    log.warning(
        "no Inception-v3 weights found under %s — using random init "
        "(features are untrained but the full pipeline is exercised)",
        cfg.model_dir,
    )
    variables = iv3.init_params(model, seed=0, image_size=image_size)
    return B.FeatureExtractor(model, variables, image_size)


class RetrainTrainer:
    def __init__(
        self,
        cfg: RetrainConfig,
        mesh=None,
        extractor: B.FeatureExtractor | None = None,
        is_chief: bool = True,
        process_index: int = 0,
        process_count: int = 1,
    ):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_devices=1)
        self.mesh_size = self.mesh.devices.size
        self.is_chief = is_chief
        self.process_index = process_index
        self.process_count = process_count

        # 1. summaries dir wipe (chief only — the reference's per-worker wipe
        # raced, retrain2/retrain2.py:368-372).
        if is_chief and os.path.isdir(cfg.summaries_dir):
            shutil.rmtree(cfg.summaries_dir)
        os.makedirs(cfg.summaries_dir, exist_ok=True)

        # 2. feature extractor.
        self.extractor = extractor or build_extractor(cfg)

        # 3. dataset split.
        self.image_lists = I.create_image_lists(
            cfg.image_dir, cfg.testing_percentage, cfg.validation_percentage
        )
        class_count = len(self.image_lists) if self.image_lists else 0
        if class_count == 0:
            raise ValueError(f"No valid folders of images found at {cfg.image_dir}")
        if class_count == 1:
            raise ValueError(
                f"Only one valid folder of images found at {cfg.image_dir} — "
                "multiple classes are needed for classification."
            )
        self.class_count = class_count
        self.do_distort = should_distort_images(
            cfg.flip_left_right, cfg.random_crop, cfg.random_scale, cfg.random_brightness
        )

        # Head model + optimizer (default sgd/constant == the reference's GD
        # at cfg.learning_rate, retrain1/retrain.py:285-287).
        from distributed_tensorflow_tpu.train.optimizers import make_optimizer

        self.head = BottleneckHead(num_classes=class_count)
        self.tx = make_optimizer(
            cfg.optimizer,
            cfg.learning_rate,
            total_steps=cfg.training_steps,
            schedule=cfg.lr_schedule,
            warmup_steps=cfg.warmup_steps,
            grad_clip_norm=cfg.grad_clip_norm,
        )
        params = self.head.init(
            jax.random.PRNGKey(cfg.seed), jnp.zeros((1, iv3.BOTTLENECK_SIZE), jnp.float32)
        )["params"]
        self.params = dp.replicate(params, self.mesh)
        self.opt_state = dp.replicate(self.tx.init(params), self.mesh)
        self.global_step = dp.replicate(jnp.zeros((), jnp.int32), self.mesh)
        self.train_step = dp.build_train_step(self._head_apply, self.tx, self.mesh)
        self.eval_step = dp.build_eval_step(self._head_apply, self.mesh)

        self.rng = np.random.default_rng(cfg.seed)
        self.distort_key = jax.random.PRNGKey(cfg.seed + 1)
        self.step_rng = jax.random.PRNGKey(cfg.seed + 2)
        self._bn_memo: dict[str, np.ndarray] = {}  # in-memory bottleneck layer

        self.train_writer = SummaryWriter(os.path.join(cfg.summaries_dir, "train")) if is_chief else None
        self.val_writer = SummaryWriter(os.path.join(cfg.summaries_dir, "validation")) if is_chief else None

        # Supervisor-parity checkpointing (retrain2/retrain2.py:423-429):
        # timed autosave of the head-training state + auto-restore on start.
        # Opt-in via --train_dir (retrain1's reference had no Supervisor).
        self.ckpt = None
        if cfg.train_dir:
            from distributed_tensorflow_tpu.train.checkpoint import (
                CheckpointManager,
                restore_replicated,
            )

            self.ckpt = CheckpointManager(
                cfg.train_dir,
                save_interval_secs=cfg.save_model_secs,
                max_to_keep=getattr(cfg, "max_to_keep", 5),
                async_snapshot=bool(getattr(cfg, "ckpt_async", 1)),
                snapshot_chunk_mb=getattr(cfg, "snapshot_chunk_mb", 64),
            )
            restored = restore_replicated(self.ckpt, self._state_dict(), self.mesh)
            if restored is not None:
                step, state = restored
                self.params = state["params"]
                self.opt_state = state["opt_state"]
                self.global_step = state["global_step"]
                log.info("restored head-training checkpoint at step %d from %s",
                         step, cfg.train_dir)

        # Resilience state (mirrors train/loop.py): per-window skipped-step
        # scalars from the non-finite guard, the consecutive-bad-window
        # counter, and the run total.
        self._window_skips: list = []
        self._bad_windows = 0
        self.total_skipped = 0

    def _state_dict(self):
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "global_step": self.global_step,
        }

    def _maybe_save(self, step: int, force: bool = False, at_boundary: bool = True) -> None:
        if self.ckpt is None:
            return
        from distributed_tensorflow_tpu.train.checkpoint import coordinated_maybe_save

        coordinated_maybe_save(
            self.ckpt, step, self._state_dict(), self.is_chief,
            force=force, at_boundary=at_boundary,
        )

    def _head_apply(self, variables, x, train=False, rngs=None):
        del rngs
        return self.head.apply(variables, x, train=train)

    # ------------------------------------------------------------------

    def cache_all_bottlenecks(self) -> int:
        """Step 4 — skipped when distorting (cache is bypassed then, parity
        with ``retrain1/retrain.py:414-418``). Multi-process: each process
        caches a stride-slice of the work (divergence from the reference's
        per-worker full duplication)."""
        if self.do_distort:
            return 0
        with obs.span("cache_all_bottlenecks"):
            if self.process_count == 1:
                return B.cache_bottlenecks(
                    self.extractor, self.image_lists, self.cfg.image_dir, self.cfg.bottleneck_dir
                )
            # Stride-sharded caching: process p takes labels p, p+P, p+2P, ...
            labels = sorted(self.image_lists.keys())
            mine = {k: self.image_lists[k] for k in labels[self.process_index :: self.process_count]}
            created = B.cache_bottlenecks(
                self.extractor, mine, self.cfg.image_dir, self.cfg.bottleneck_dir
            )
            from distributed_tensorflow_tpu.parallel.distributed import barrier

            barrier("bottleneck_cache")
            return created

    def _sample(self, how_many: int, category: str):
        cfg = self.cfg
        if self.do_distort and category == "training":
            b, t = B.get_random_distorted_bottlenecks(
                self.extractor,
                self.image_lists,
                how_many,
                category,
                cfg.image_dir,
                self.rng,
                self._next_distort_key(),
                cfg.flip_left_right,
                cfg.random_crop,
                cfg.random_scale,
                cfg.random_brightness,
            )
            return b, t, []
        return B.get_random_cached_bottlenecks(
            self.extractor, self.image_lists, how_many, category,
            cfg.bottleneck_dir, cfg.image_dir, self.rng, memo=self._bn_memo,
        )

    def _next_distort_key(self):
        self.distort_key, sub = jax.random.split(self.distort_key)
        return sub

    def _eval_batch(self, bottlenecks, truths):
        padded, n = dp.pad_to_multiple(
            {"image": bottlenecks, "label": truths}, self.mesh_size
        )
        # Sampling is seed-deterministic — every process holds the same batch.
        correct, loss_sum = self.eval_step(
            self.params, dp.shard_global_batch(padded, self.mesh)
        )
        return float(correct) / n, float(loss_sum) / n

    # ------------------------------------------------------------------

    def train(self):
        cfg = self.cfg
        clock = WallClock()
        created = self.cache_all_bottlenecks()
        if created:
            log.info("cached %d bottlenecks in %.1fs", created, clock.elapsed)

        # Round the train batch up to a mesh multiple (sampling is
        # with-replacement, so a slightly larger batch is semantically clean;
        # padding with zero-label rows would instead skew the loss mean).
        train_bs = -(-cfg.train_batch_size // self.mesh_size) * self.mesh_size

        step = int(jax.device_get(self.global_step))
        reg = obs.get_registry()
        obs_steps = reg.counter(
            "retrain_steps_total", "Head-training optimizer steps completed.")
        obs_skipped = reg.counter(
            "retrain_skipped_nonfinite_total",
            "Head-training steps skipped by the non-finite guard.")
        with resilience.PreemptionGuard() as guard:
            while step < cfg.training_steps:
                bottlenecks, truths, _ = self._sample(train_bs, "training")
                # Fault site ``nonfinite_grad:step=N`` — exercise the guard.
                if faults.fire_step("nonfinite_grad", [step]):
                    bottlenecks = np.full_like(bottlenecks, np.nan)
                batch = dp.shard_global_batch(
                    {"image": bottlenecks, "label": truths}, self.mesh
                )
                # Base key only — the per-step fold happens on-device in the jitted
                # step, keyed on global_step.
                self.params, self.opt_state, self.global_step, metrics = self.train_step(
                    self.params, self.opt_state, self.global_step, batch, self.step_rng
                )
                skipped = metrics.get("skipped_nonfinite")
                if skipped is not None:
                    self._window_skips.append(skipped)
                step += 1
                obs_steps.inc()
                is_last = step == cfg.training_steps
                at_boundary = step % cfg.eval_step_interval == 0 or is_last
                if faults.fire_step("preempt", [step]):
                    guard.request()
                if guard.should_exit(at_boundary):
                    log.warning(
                        "preemption at step %d — emergency checkpoint, then "
                        "clean stop", step,
                    )
                    with obs.span("emergency_shutdown", step=step,
                                  reason="preempt"):
                        self._maybe_save(step, force=True)
                    resilience.dump_flight_record("preempt")
                    break
                window_skipped = 0
                if at_boundary:
                    parts, self._window_skips = self._window_skips, []
                    window_skipped = int(round(sum(
                        float(jax.device_get(x)) for x in parts
                    )))
                    self.total_skipped += window_skipped
                    if window_skipped:
                        obs_skipped.inc(window_skipped)
                        self._bad_windows += 1
                        log.warning(
                            "eval window ending at step %d skipped %d "
                            "non-finite step(s) (%d consecutive)",
                            step, window_skipped, self._bad_windows,
                        )
                    else:
                        self._bad_windows = 0
                    if (
                        window_skipped
                        and getattr(cfg, "rollback_bad_windows", 0) > 0
                        and self._bad_windows >= cfg.rollback_bad_windows
                        and self.ckpt is not None
                        and self.ckpt.latest_step() is not None
                    ):
                        from distributed_tensorflow_tpu.train.checkpoint import (
                            restore_replicated,
                        )

                        # Rollback must land pre-divergence: cancel any
                        # queued snapshot before draining into the restore.
                        self.ckpt.veto_pending()
                        restored = restore_replicated(
                            self.ckpt, self._state_dict(), self.mesh
                        )
                        if restored is not None:
                            rb_step, state = restored
                            self.params = state["params"]
                            self.opt_state = state["opt_state"]
                            self.global_step = state["global_step"]
                            self._bad_windows = 0
                            log.warning(
                                "rolled back head training to checkpoint "
                                "step %d after %d bad window(s)",
                                rb_step, cfg.rollback_bad_windows,
                            )
                            obs.trace_event("rollback", from_step=step,
                                            to_step=int(rb_step))
                            resilience.dump_flight_record("rollback")
                            step = int(rb_step)
                            continue
                # Bad windows don't advance the checkpoint chain (rollback
                # must land before the divergence started) — including any
                # snapshot still queued from inside the window.
                if window_skipped:
                    if self.ckpt is not None:
                        self.ckpt.veto_pending()
                else:
                    self._maybe_save(step, at_boundary=at_boundary)
                if at_boundary:
                    m = jax.device_get(metrics)
                    train_acc, train_ce = float(m["accuracy"]), float(m["loss"])
                    vb, vt, _ = self._sample(cfg.validation_batch_size, "validation")
                    val_acc, val_ce = self._eval_batch(vb, vt)
                    log.info(
                        "%s: Step %d: Train accuracy = %.1f%%  Cross entropy = %f  "
                        "Validation accuracy = %.1f%%",
                        time.strftime("%Y-%m-%d %H:%M:%S"), step,
                        train_acc * 100, train_ce, val_acc * 100,
                    )
                    if self.train_writer:
                        self.train_writer.add_scalars(
                            {"accuracy": train_acc, "cross_entropy": train_ce}, step
                        )
                        self.val_writer.add_scalars(
                            {"accuracy": val_acc, "cross_entropy": val_ce}, step
                        )
                    obs.update_memory_gauges()
                    obs_dir = getattr(cfg, "obs_dir", "")
                    if obs_dir:
                        try:
                            obs.write_process_snapshot(obs_dir)
                            if self.is_chief:
                                agg = obs.FleetAggregator()
                                if agg.load_dir(obs_dir):
                                    agg.export(obs_dir)
                        except OSError:
                            pass
        self._maybe_save(step, force=True)
        train_time = clock.elapsed
        log.info("Training time: %.2fs", train_time)

        # Final full test eval (test_batch_size default -1 = whole set).
        tb, tt, tfiles = self._sample(cfg.test_batch_size, "testing")
        test_acc, _ = self._eval_batch(tb, tt)
        log.info("Final test accuracy = %.1f%% (N=%d)", test_acc * 100, len(tb))
        if cfg.print_misclassified_test_images:
            self._print_misclassified(tb, tt, tfiles)

        if self.is_chief:
            self.export()
        if self.train_writer:
            self.train_writer.close()
            self.val_writer.close()
        return {"test_accuracy": test_acc, "seconds": train_time, "steps": step}

    def _print_misclassified(self, bottlenecks, truths, filenames):
        """``--print_misclassified_test_images`` — parsed-but-dead in the
        reference (SURVEY §7 defect list); functional here."""
        logits = np.asarray(
            self.head.apply({"params": jax.device_get(self.params)}, jnp.asarray(bottlenecks))
        )
        preds = logits.argmax(-1)
        labels = np.asarray(truths).argmax(-1)
        label_names = list(self.image_lists.keys())
        log.info("=== MISCLASSIFIED TEST IMAGES ===")
        for fname, p, t in zip(filenames, preds, labels):
            if p != t:
                log.info("%s: predicted %s, true %s", fname, label_names[p], label_names[t])

    def export(self):
        """Params bundle + labels txt (frozen-graph export parity,
        ``retrain1/retrain.py:470-475``)."""
        cfg = self.cfg
        with obs.span("export", path=cfg.output_graph):
            export_inference_bundle(
                cfg.output_graph,
                jax.device_get(self.params),
                labels=list(self.image_lists.keys()),
                labels_path=cfg.output_labels,
                metadata={
                    "model": "BottleneckHead",
                    "num_classes": self.class_count,
                    "final_tensor_name": cfg.final_tensor_name,
                    "bottleneck_size": iv3.BOTTLENECK_SIZE,
                },
            )
        log.info("exported %s and %s", cfg.output_graph, cfg.output_labels)
        if cfg.export_stablehlo:
            from distributed_tensorflow_tpu.train.checkpoint import export_frozen_classifier

            hlo_path = cfg.output_graph + ".stablehlo"
            export_frozen_classifier(
                hlo_path, self.head.apply, self.params, (iv3.BOTTLENECK_SIZE,),
                metadata={"num_classes": self.class_count},
            )
            log.info("exported frozen StableHLO program %s", hlo_path)
