"""Optimizer + learning-rate-schedule factory.

The reference hardcodes two optimizers: ``AdamOptimizer(1e-4)`` for the MNIST
demos (``demo1/train.py:132``) and ``GradientDescentOptimizer(FLAGS.
learning_rate)`` for retrain (``retrain1/retrain.py:285-287``), both at a
constant rate. Those stay the defaults (parity); this module adds the
schedule/optimizer selection a framework needs — optax transforms compose
into the jitted train step like any other pure function, so a schedule costs
nothing at runtime (the step count rides the optimizer state).

Schedules take ``total_steps`` because cosine needs the horizon; ``constant``
ignores it.
"""

from __future__ import annotations

import optax

OPTIMIZERS = ("adam", "adamw", "sgd", "momentum")
SCHEDULES = ("constant", "cosine", "warmup_cosine", "linear")


def make_schedule(
    name: str,
    learning_rate: float,
    total_steps: int,
    warmup_steps: int = 0,
    final_scale: float = 0.0,
) -> optax.Schedule:
    """Build a learning-rate schedule.

    ``final_scale`` is the end-of-training rate as a fraction of the peak
    (cosine/linear decay to ``learning_rate * final_scale``).
    """
    if name == "constant":
        return optax.constant_schedule(learning_rate)
    if name == "cosine":
        return optax.cosine_decay_schedule(
            learning_rate, max(total_steps, 1), alpha=final_scale
        )
    if name == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=learning_rate,
            warmup_steps=max(warmup_steps, 1),
            decay_steps=max(total_steps, warmup_steps + 1),
            end_value=learning_rate * final_scale,
        )
    if name == "linear":
        return optax.linear_schedule(
            learning_rate, learning_rate * final_scale, max(total_steps, 1)
        )
    raise ValueError(f"unknown schedule {name!r} (choices: {SCHEDULES})")


def make_optimizer(
    name: str,
    learning_rate: float,
    total_steps: int,
    schedule: str = "constant",
    warmup_steps: int = 0,
    weight_decay: float = 1e-4,
    momentum: float = 0.9,
    grad_clip_norm: float = 0.0,
) -> optax.GradientTransformation:
    """Build the train-step optimizer.

    ``grad_clip_norm > 0`` prepends global-norm clipping (computed on the
    already-psum-averaged gradients inside the jitted step).
    """
    if schedule == "constant":
        # A plain float, NOT constant_schedule: a schedule adds a
        # ScaleByScheduleState(count) leaf to the opt state, which would
        # break restoring checkpoints written by the pre-factory optimizers.
        lr = learning_rate
    else:
        lr = make_schedule(schedule, learning_rate, total_steps, warmup_steps)
    if name == "adam":
        tx = optax.adam(lr)
    elif name == "adamw":
        tx = optax.adamw(lr, weight_decay=weight_decay)
    elif name == "sgd":
        tx = optax.sgd(lr)
    elif name == "momentum":
        tx = optax.sgd(lr, momentum=momentum)
    else:
        raise ValueError(f"unknown optimizer {name!r} (choices: {OPTIMIZERS})")
    if grad_clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx
