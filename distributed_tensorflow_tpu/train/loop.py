"""MNIST trainer — the demo1/demo2 training loop, TPU-native.

One loop serves both the single-device (``demo1/train.py:149-165``) and
distributed (``demo2/train.py:176-193``) workloads: the only difference is the
mesh it runs over. Structure parity with the reference:

  * ``training_steps`` steps of batch-``batch_size`` Adam updates
  * full test-set + train-set accuracy eval every ``eval_step_interval``
    (reference evals *inside* the hot loop at ``demo1/train.py:158-163`` with
    full-dataset feed_dict runs — here eval is a separate jitted sharded
    program and the hot loop stays free of host transfers)
  * scalar/histogram summaries per eval (not per step: a per-step host sync
    would stall the TPU pipeline; divergence documented)
  * timed checkpoint autosave + restore-on-start (Supervisor parity)
  * wall-clock ``Training time`` print (``demo1/train.py:164``)
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.config import MnistTrainConfig
from distributed_tensorflow_tpu.data.mnist import DataSet, read_data_sets
from distributed_tensorflow_tpu.data.prefetch import (
    bounded_device_batches,
    stacked_device_batches,
)
from distributed_tensorflow_tpu.models.mnist_cnn import MnistCNN
from distributed_tensorflow_tpu.parallel import data_parallel as dp
from distributed_tensorflow_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_tpu.train import resilience
from distributed_tensorflow_tpu.train.checkpoint import CheckpointManager
from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.logging import get_logger
from distributed_tensorflow_tpu.utils.profiler import Profiler
from distributed_tensorflow_tpu.utils.summary import SummaryWriter, variable_summaries
from distributed_tensorflow_tpu.utils.timer import StepTimer, WallClock

log = get_logger(__name__)


def build_model(cfg: MnistTrainConfig):
    """cfg.model selects the MNIST classifier family: the reference convnet
    (``demo1/train.py:49-123`` shape) or the ViT (``models/vit.py``) — same
    (B, 784) apply convention, same trainer/ckpt/export machinery."""
    from distributed_tensorflow_tpu.models import digit_classifier

    kwargs = {"dropout_rate": cfg.dropout_rate}
    if cfg.model in ("vit", "ViT"):
        kwargs["remat"] = cfg.remat
    return digit_classifier(cfg.model, **kwargs)


class MnistTrainer:
    @staticmethod
    def _resolve_data_dir(cfg: MnistTrainConfig) -> str:
        """Real-data convenience (C19 spirit): with ``--t10k_split`` and
        ``--data_dir`` left at its parser default, fall back to the repo's
        bundled genuine t10k files so the demo runs bare from any cwd. An
        explicitly passed data_dir is never redirected."""
        if cfg.t10k_split:
            import os

            from distributed_tensorflow_tpu.data.mnist import (
                TEST_IMAGES,
                bundled_mnist_dir,
            )
            from distributed_tensorflow_tpu.utils.assets import dataclass_default

            if (
                not os.path.exists(os.path.join(cfg.data_dir, TEST_IMAGES))
                and cfg.data_dir == dataclass_default(MnistTrainConfig, "data_dir")
                and bundled_mnist_dir()
            ):
                log.info(
                    "%s has no t10k files; using bundled real MNIST %s",
                    cfg.data_dir, bundled_mnist_dir(),
                )
                return bundled_mnist_dir()
        return cfg.data_dir

    def __init__(
        self,
        cfg: MnistTrainConfig,
        mesh=None,
        datasets=None,
        model: MnistCNN | None = None,
        is_chief: bool = True,
        eval_chunk: int = 2000,
        scale_batch_by_mesh: bool = True,
    ):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_devices=1)
        self.model = model if model is not None else build_model(cfg)
        self.datasets = datasets or read_data_sets(
            self._resolve_data_dir(cfg),
            one_hot=True,
            seed=cfg.seed,
            synthetic=cfg.synthetic_data,
            download=cfg.download_data,
            t10k_split=cfg.t10k_split,
        )
        self.is_chief = is_chief
        self.eval_chunk = eval_chunk
        self.mesh_size = self.mesh.devices.size
        # Reference demo2 semantics: each of n async workers consumed
        # batch_size examples per step; the sync-SPMD equivalent is a global
        # batch of batch_size × mesh_size (each device computes one
        # batch_size shard). With a 1-device mesh this is exactly demo1.
        if scale_batch_by_mesh:
            self.global_batch = cfg.batch_size * self.mesh_size
        else:
            if cfg.batch_size % self.mesh_size:
                raise ValueError(
                    f"batch_size {cfg.batch_size} not divisible by mesh size {self.mesh_size}"
                )
            self.global_batch = cfg.batch_size
        # Multi-process: each worker samples its own share of the global batch
        # independently (reference demo2 parity — independent per-worker
        # shuffles), so the host pipeline assembles feed_batch examples here
        # and each process gets a decorrelated shuffle stream over the same
        # dataset copy.
        self.feed_batch = self.global_batch // jax.process_count()
        if jax.process_count() > 1:
            self.datasets.train.reseed_shuffle(cfg.seed + 1000003 * jax.process_index())

        # Default adam/constant == demo1/train.py:132 parity.
        from distributed_tensorflow_tpu.train.optimizers import make_optimizer

        self.tx = make_optimizer(
            cfg.optimizer,
            cfg.learning_rate,
            total_steps=cfg.training_steps,
            schedule=cfg.lr_schedule,
            warmup_steps=cfg.warmup_steps,
            grad_clip_norm=cfg.grad_clip_norm,
        )
        self.rng = jax.random.PRNGKey(cfg.seed)

        params = self.model.init(
            jax.random.PRNGKey(cfg.seed), jnp.zeros((1, 784), jnp.float32), train=False
        )["params"]
        opt_state = self.tx.init(params)
        self.params = dp.replicate(params, self.mesh)
        self.opt_state = dp.replicate(opt_state, self.mesh)
        self.global_step = dp.replicate(jnp.zeros((), jnp.int32), self.mesh)

        self._guard = bool(getattr(cfg, "guard_nonfinite", 1))
        self.train_step = dp.build_train_step(
            self.model.apply, self.tx, self.mesh, guard_nonfinite=self._guard
        )
        if cfg.accum_steps > 1 and (cfg.steps_per_call > 1 or cfg.device_data):
            raise ValueError(
                "accum_steps>1 is exclusive with steps_per_call>1 / device_data "
                "(accumulation trades dispatches for memory; fusion trades the "
                "other way)"
            )
        self.multi_step = (
            dp.build_multi_step(self.model.apply, self.tx, self.mesh, guard_nonfinite=self._guard)
            if cfg.steps_per_call > 1
            else None
        )
        self.accum_step = (
            dp.build_accum_train_step(self.model.apply, self.tx, self.mesh, guard_nonfinite=self._guard)
            if cfg.accum_steps > 1
            else None
        )
        self.eval_step = dp.build_eval_step(self.model.apply, self.mesh)

        self.ckpt = CheckpointManager(
            cfg.log_dir,
            save_interval_secs=cfg.save_model_secs,
            max_to_keep=getattr(cfg, "max_to_keep", 5),
            async_snapshot=bool(getattr(cfg, "ckpt_async", 1)),
            snapshot_chunk_mb=getattr(cfg, "snapshot_chunk_mb", 64),
        )
        self.writer = SummaryWriter(cfg.log_dir) if is_chief else None

        # Resilience state: lazily-accumulated per-window skipped-step
        # scalars (device arrays — summed/fetched only at eval boundaries so
        # the hot loop stays sync-free), the consecutive-bad-window counter
        # driving rollback, and the preemption guard (installed for the
        # duration of train()).
        self._window_skips: list = []
        self._bad_windows = 0
        self._rollbacks = 0
        self.total_skipped = 0
        self._preempt: resilience.PreemptionGuard | None = None

        # Observability: crash dumps go to cfg.obs_dir when set, and the
        # step-time decomposition is published into the process registry at
        # eval boundaries (counters are window DELTAS of the shared
        # data-wait counter and the checkpoint stall accumulator — the
        # compute slice is what's left of the window wall time).
        if getattr(cfg, "obs_dir", ""):
            obs.set_dump_dir(cfg.obs_dir)
        reg = obs.get_registry()
        self._obs_wait = reg.counter(
            "data_wait_seconds_total",
            "Seconds the training thread blocked waiting for input batches.")
        self._obs_compute = reg.counter(
            "train_compute_seconds_total",
            "Window wall time minus data-wait and checkpoint stall.")
        self._obs_stall = reg.counter(
            "train_ckpt_stall_seconds_total",
            "Main-thread seconds blocked inside checkpoint save paths.")
        self._obs_steps = reg.counter(
            "train_steps_total", "Optimizer steps completed.")
        self._obs_skipped = reg.counter(
            "train_skipped_nonfinite_total",
            "Steps skipped by the non-finite guard.")
        self._obs_examples_rate = reg.gauge(
            "train_examples_per_sec",
            "Global examples/s over the last drained training window.")
        self._obs_wait_frac = reg.gauge(
            "train_data_wait_frac",
            "Data-wait share of the last window's wall time (the "
            "input-bound alarm the default training SLO watches).")
        self._perf = obs.PerfGauges(reg)
        slo_rules = obs.parse_slo_flag(
            getattr(cfg, "slo", ""),
            defaults=obs.default_training_rules)
        # Evaluated at eval boundaries (no ticker thread: the train loop
        # already has a natural heartbeat, and a wall-clock ticker would
        # race the window bookkeeping for no fresher data).
        self._slo = obs.SloMonitor(reg, slo_rules) if slo_rules else None
        self._win_t0 = 0.0
        self._win_wait_base = 0.0
        self._win_stall_base = 0.0

        # Supervisor parity: init-or-restore from logdir (demo2/train.py:166-176).
        from distributed_tensorflow_tpu.train.checkpoint import restore_replicated

        restored = restore_replicated(self.ckpt, self._state_dict(), self.mesh)
        if restored is not None:
            step, state = restored
            self.params = state["params"]
            self.opt_state = state["opt_state"]
            self.global_step = state["global_step"]
            log.info("restored checkpoint at step %d from %s", step, cfg.log_dir)

    # -- state (de)serialization ------------------------------------------------

    def _state_dict(self):
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "global_step": self.global_step,
        }

    # (restore in __init__ goes through checkpoint.restore_replicated;
    # saves go through checkpoint.coordinated_maybe_save below.)

    # -- eval ------------------------------------------------------------------

    def evaluate(self, dataset: DataSet, max_examples: int | None = None):
        """Exact full-dataset accuracy/loss via chunked sharded eval."""
        images, labels = dataset.images, dataset.labels
        if max_examples is not None:
            images, labels = images[:max_examples], labels[:max_examples]
        total_correct = total_loss = 0.0
        n = images.shape[0]
        for lo in range(0, n, self.eval_chunk):
            chunk = {"image": images[lo : lo + self.eval_chunk], "label": labels[lo : lo + self.eval_chunk]}
            padded, real = dp.pad_to_multiple(chunk, self.mesh_size)
            # Every process holds the same dataset copy — identical-data path.
            batch = dp.shard_global_batch(padded, self.mesh)
            correct, loss_sum = self.eval_step(self.params, batch)
            total_correct += float(correct)
            total_loss += float(loss_sum)
        return total_correct / n, total_loss / n

    # -- train -----------------------------------------------------------------

    def train(self, num_steps: int | None = None):
        cfg = self.cfg
        num_steps = num_steps if num_steps is not None else cfg.training_steps
        clock = WallClock()
        # Boundary-drained timing: the timer ticks ONLY in _post_step at
        # eval boundaries, right after the metrics device_get forces every
        # queued dispatch to complete — per-dispatch ticks through the axon
        # tunnel measure issue time, not compute (bench.py docstring), and
        # warmup=2 drops the first measured window (it contains the jit
        # compile).
        timer = StepTimer(warmup_steps=2)
        step = start_step = int(jax.device_get(self.global_step))
        timer.start(step)
        self._bad_windows = 0
        self._window_skips = []
        guard = resilience.PreemptionGuard() if getattr(cfg, "preempt_save", 1) else None
        if guard is not None:
            self._preempt = guard.install()
        self._reset_window_obs(step)
        preempted = False
        try:
            while step < num_steps:
                try:
                    self._run_training(step, num_steps, timer)
                except resilience.Preempted as p:
                    # Fall through to the forced save below: that IS the
                    # coordinated emergency checkpoint, after which we return
                    # cleanly so a restart resumes via restore_replicated.
                    log.warning(
                        "preemption at step %d — emergency checkpoint, then "
                        "clean exit", p.step,
                    )
                    preempted = True
                    break
                except resilience.RollbackRequested as rb:
                    self._rollbacks += 1
                    if self._rollbacks > getattr(cfg, "max_rollbacks", 3):
                        raise RuntimeError(
                            f"giving up after {self._rollbacks - 1} rollbacks: "
                            f"{rb}"
                        ) from rb
                    if not self._rollback(rb, timer):
                        log.error(
                            "rollback requested but no checkpoint to restore "
                            "— continuing from current state"
                        )
                step = int(jax.device_get(self.global_step))
        finally:
            if guard is not None:
                guard.uninstall()
            self._preempt = None
        step = int(jax.device_get(self.global_step))
        if preempted:
            # The emergency-shutdown span wraps the coordinated forced save
            # so the flight record a preemption ships shows both: the
            # shutdown envelope and the checkpoint_save span nested in it.
            with obs.span("emergency_shutdown", step=step, reason="preempt"):
                self._maybe_save(step, force=True)
            resilience.dump_flight_record("preempt")
        else:
            self._maybe_save(step, force=True)
        if self.is_chief and self.writer:
            self.writer.flush()
        train_time = clock.elapsed
        rate = timer.steps_per_sec
        if rate <= 0 and train_time > 0:
            # Run too short for a post-compile drained window (single eval
            # boundary): fall back to whole-run wall-clock — an honest
            # LOWER bound since it includes compile and evals.
            rate = (step - start_step) / train_time
            basis = "whole run incl. compile/eval — run longer for a clean rate"
        else:
            basis = "drained training windows; wall-clock includes eval/compile"
        log.info("Training time: %.2fs (%.1f steps/s, %s)", train_time, rate, basis)
        return {
            "steps": step,
            "seconds": train_time,
            "steps_per_sec": rate,
            # Main-thread time blocked inside save paths (the zero-stall
            # pipeline's own measure of what autosave cost the loop).
            "ckpt_stall_seconds": round(self.ckpt.stall_seconds, 4),
        }

    def _run_training(self, step: int, num_steps: int, timer: StepTimer) -> None:
        """One attempt at running [step, num_steps): builds the input
        pipeline and drives the hot loop. Preemption/rollback propagate as
        exceptions (input pipeline and profiler are closed on the way out);
        ``train()`` owns the recovery policy."""
        cfg = self.cfg
        if cfg.device_data:
            self._train_loop(None, num_steps, step, timer)
            return
        # Background input pipeline: batch assembly + HBM transfer
        # overlap the device step (replaces the reference's serial
        # feed_dict upload, demo1/train.py:153-155).
        if self.multi_step is not None:
            chunks = self._chunk_sizes(step, num_steps)
            prefetch = stacked_device_batches(
                self.datasets.train, self.feed_batch, self.mesh, chunks
            )
        elif self.accum_step is not None:
            # k microbatches per optimizer step, stacked on a leading
            # dim (the accum step scans over them).
            prefetch = stacked_device_batches(
                self.datasets.train,
                self.feed_batch,
                self.mesh,
                [self.cfg.accum_steps] * (num_steps - step),
            )
        else:
            prefetch = bounded_device_batches(
                self.datasets.train, self.feed_batch, self.mesh, num_steps - step
            )
        try:
            self._train_loop(prefetch, num_steps, step, timer)
        finally:
            prefetch.close()

    def _rollback(self, rb: "resilience.RollbackRequested", timer: StepTimer) -> bool:
        """Restore the last good checkpoint after a rollback request; returns
        False when there is nothing to restore."""
        from distributed_tensorflow_tpu.train.checkpoint import restore_replicated

        self._bad_windows = 0
        self._window_skips = []
        # A snapshot queued during the diverging window must not complete
        # into the step we are rolling away from (restore itself drains
        # whatever already reached the write stage).
        self.ckpt.veto_pending()
        restored = restore_replicated(self.ckpt, self._state_dict(), self.mesh)
        if restored is None:
            return False
        step, state = restored
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.global_step = state["global_step"]
        timer.mark(int(step))
        self._reset_window_obs(int(step))
        log.warning("rolled back to checkpoint step %d (%s)", step, rb)
        obs.trace_event("rollback", from_step=rb.step, to_step=int(step),
                        bad_windows=rb.bad_windows)
        resilience.dump_flight_record("rollback")
        return True

    # -- window observability ---------------------------------------------

    def _reset_window_obs(self, step: int) -> None:
        self._win_t0 = time.perf_counter()
        self._win_step_base = step
        self._win_wait_base = self._obs_wait.value
        self._win_stall_base = self.ckpt.stall_seconds

    def _publish_window_obs(self, step: int, steps_per_sec: float,
                            window_skipped: int) -> None:
        """Decompose the window just drained: wall = data-wait + checkpoint
        stall + (residual) device compute. The wait/stall slices are deltas
        of their process accumulators, so they are measured, not inferred."""
        wall = time.perf_counter() - self._win_t0
        wait_d = max(self._obs_wait.value - self._win_wait_base, 0.0)
        stall_d = max(self.ckpt.stall_seconds - self._win_stall_base, 0.0)
        compute = max(wall - wait_d - stall_d, 0.0)
        self._obs_compute.inc(compute)
        self._obs_stall.inc(stall_d)
        self._obs_steps.inc(max(step - self._win_step_base, 0))
        if window_skipped:
            self._obs_skipped.inc(window_skipped)
        if steps_per_sec > 0:
            self._obs_examples_rate.set(steps_per_sec * self.global_batch)
            self._perf.update_window(
                steps_per_sec=steps_per_sec,
                examples_per_step=self.global_batch,
            )
        if wall > 0:
            self._obs_wait_frac.set(wait_d / wall)
        obs.update_memory_gauges()  # no-op readings on CPU (graceful null)
        if self._slo is not None:
            self._slo.evaluate()
        obs_dir = getattr(self.cfg, "obs_dir", "")
        if obs_dir:
            # Fleet plane: every process drops its snapshot; the chief
            # merges whatever snapshots exist so far into the fleet view.
            try:
                obs.write_process_snapshot(obs_dir)
                if self.is_chief:
                    agg = obs.FleetAggregator()
                    if agg.load_dir(obs_dir):
                        agg.export(obs_dir)
            except OSError:
                pass  # observability must never kill the train step
        self._reset_window_obs(step)

    def _train_loop(self, prefetch, num_steps: int, step: int, timer: StepTimer) -> None:
        cfg = self.cfg
        # Chief-only trace (SURVEY §5.1): replaces the reference's wall-clock
        # prints with a real per-op device timeline when --profile_dir is set.
        # The window is relative to THIS run's first step (``step`` may be a
        # checkpoint-resumed global step); the sync callback flushes the
        # async-dispatched device queue so the XPlane isn't truncated.
        prof = Profiler(
            cfg.profile_dir if self.is_chief else None,
            start_step=step + cfg.profile_start_step,
            num_steps=cfg.profile_num_steps,
            # device_get, NOT block_until_ready: the latter returns without
            # waiting once dispatches queue on the axon tunnel, truncating
            # the trace (same honest barrier as tools/train_lm.py).
            sync=lambda: jax.device_get(self.global_step),
        )
        try:
            self._train_steps(prefetch, num_steps, step, timer, prof)
        finally:
            prof.close()

    def _chunk_sizes(self, step: int, num_steps: int) -> list[int]:
        """Fused-dispatch sizes: ``steps_per_call`` steps per call, clipped so
        no call crosses an eval boundary or the end of training (eval needs
        up-to-date params on the host side of a call)."""
        interval = self.cfg.eval_step_interval
        chunks, s = [], step
        while s < num_steps:
            boundary = min(num_steps, ((s // interval) + 1) * interval)
            k = min(self.cfg.steps_per_call, boundary - s)
            chunks.append(k)
            s += k
        return chunks

    def _train_steps(self, prefetch, num_steps: int, step: int, timer: StepTimer, prof) -> None:
        if prefetch is None:
            self._train_steps_device_data(num_steps, step, timer, prof)
            return
        while step < num_steps:
            batch = next(prefetch)
            # Fused dispatches advance `span` steps per call; the profiler
            # window intersects [step, step+span), not just [step, step+1).
            k = (
                next(iter(batch.values())).shape[0]
                if self.multi_step is not None
                else 1  # accum: k microbatches but ONE optimizer step
            )
            # Fault site ``nonfinite_grad:step=N``: NaN the dispatch covering
            # step N so the grads go non-finite and the guard path (skip +
            # metric + rollback policy) is exercised for real.
            if faults.fire_step("nonfinite_grad", range(step, step + k)):
                batch = {**batch, "image": batch["image"] * jnp.float32(jnp.nan)}
            # Base key only: the step fold happens on-device inside the jitted
            # program (keyed on global_step), so the hot loop does zero
            # per-step host dispatches besides the train step itself.
            with prof.step(step, span=k):
                if self.multi_step is not None:
                    self.params, self.opt_state, self.global_step, metrics = self.multi_step(
                        self.params, self.opt_state, self.global_step, batch, self.rng
                    )
                    self._note_skips(metrics)
                    # Stacked (k,) metrics → report the final step's values,
                    # matching what a per-step loop would log at this point.
                    metrics = {name: v[-1] for name, v in metrics.items()}
                elif self.accum_step is not None:
                    self.params, self.opt_state, self.global_step, metrics = self.accum_step(
                        self.params, self.opt_state, self.global_step, batch, self.rng
                    )
                    self._note_skips(metrics)
                else:
                    self.params, self.opt_state, self.global_step, metrics = self.train_step(
                        self.params, self.opt_state, self.global_step, batch, self.rng
                    )
                    self._note_skips(metrics)
            step += k
            self._post_step(step, num_steps, metrics, timer)

    def _train_steps_device_data(self, num_steps: int, step: int, timer: StepTimer, prof) -> None:
        """Hot loop with the training set resident in HBM: one pool upload,
        then per-dispatch fused steps whose batches are gathered on device
        (``dp.build_pool_train_fn``) — no host input work at all."""
        train = self.datasets.train
        pool = dp.shard_pool(train.images, train.labels, self.mesh)
        batch_per_shard = self.global_batch // self.mesh_size
        fns: dict[int, object] = {}  # one compiled program per distinct k
        for k in set(self._chunk_sizes(step, num_steps)):
            fns[k] = dp.build_pool_train_fn(
                self.model.apply, self.tx, self.mesh, batch_per_shard, k,
                guard_nonfinite=self._guard,
            )
        for k in self._chunk_sizes(step, num_steps):
            with prof.step(step, span=k):
                self.params, self.opt_state, self.global_step, metrics = fns[k](
                    self.params, self.opt_state, self.global_step, pool, self.rng
                )
            self._note_skips(metrics)
            # Lazy on-device slice — no host sync in the hot loop; _post_step
            # device_gets at eval cadence only.
            metrics = {name: v[-1] for name, v in metrics.items()}
            step += k
            self._post_step(step, num_steps, metrics, timer)

    def _note_skips(self, metrics) -> None:
        """Queue this dispatch's skipped-step count (scalar or stacked) for
        the window aggregate — a device-side sum, NO host sync here."""
        s = metrics.get("skipped_nonfinite")
        if s is not None:
            self._window_skips.append(jnp.sum(s))

    def _drain_window_skips(self) -> int:
        """Total non-finite-skipped steps since the last eval boundary
        (fetches the queued device scalars — call at boundaries only)."""
        parts, self._window_skips = self._window_skips, []
        if not parts:
            return 0
        return int(round(sum(float(jax.device_get(x)) for x in parts)))

    def _post_step(self, step: int, num_steps: int, metrics, timer: StepTimer) -> None:
        cfg = self.cfg
        at_boundary = step % cfg.eval_step_interval == 0 or step == num_steps
        # Preemption first: a pending SIGTERM means save-and-exit beats one
        # more eval. Fault site ``preempt:step=N`` feeds the same flag a real
        # signal sets.
        if self._preempt is not None:
            if faults.fire_step("preempt", [step]):
                self._preempt.request()
            if self._preempt.should_exit(at_boundary):
                obs.trace_event("preempt_exit", step=step)
                raise resilience.Preempted(step)
        window_skipped = 0
        if at_boundary:
            m = jax.device_get(metrics)  # completion barrier for the window
            timer.tick_to(step)
            window_skipped = self._drain_window_skips()
            self.total_skipped += window_skipped
            if window_skipped:
                self._bad_windows += 1
                log.warning(
                    "eval window ending at step %d skipped %d non-finite "
                    "step(s) (%d consecutive bad window(s))",
                    step, window_skipped, self._bad_windows,
                )
            else:
                self._bad_windows = 0
            rate = timer.steps_per_sec  # 0.0 until the compile window passes
            # Decompose the drained window BEFORE eval/summary work so the
            # compute slice covers training dispatches only.
            self._publish_window_obs(step, rate, window_skipped)
            test_acc, test_loss = self.evaluate(self.datasets.test)
            train_acc, _ = self.evaluate(self.datasets.train, max_examples=10000)
            log.info(
                "step %d: batch loss %.4f, test acc %.4f, train acc %.4f (%s)",
                step, float(m["loss"]), test_acc, train_acc,
                f"{rate:.1f} steps/s" if rate > 0 else "steps/s pending",
            )
            if self.writer:
                self.writer.add_scalars(
                    {
                        "cross_entropy": float(m["loss"]),
                        "batch_accuracy": float(m["accuracy"]),
                        "test_accuracy": test_acc,
                        "test_loss": test_loss,
                        "train_accuracy": train_acc,
                        "skipped_nonfinite": float(window_skipped),
                        **({"steps_per_sec": rate} if rate > 0 else {}),
                    },
                    step,
                )
                # variable_summaries parity (demo1/train.py:15-24) at eval
                # cadence, for the classifier-head weights (fc2 on the
                # convnet; the ViT's head otherwise).
                p = jax.device_get(self.params)
                head_name = "fc2" if "fc2" in p else "head"
                if head_name in p and "kernel" in p[head_name]:
                    variable_summaries(
                        self.writer, f"{head_name}/weights",
                        p[head_name]["kernel"], step,
                    )
        if (
            at_boundary
            and window_skipped
            and getattr(cfg, "rollback_bad_windows", 0) > 0
            and self._bad_windows >= cfg.rollback_bad_windows
            and self.ckpt.latest_step() is not None
        ):
            # K consecutive windows of skipped updates = a diverged run the
            # guard alone can't rescue; train() restores the last good
            # checkpoint. (The bad-window save suppression below keeps the
            # latest checkpoint pre-divergence.)
            raise resilience.RollbackRequested(step, self._bad_windows)
        if at_boundary and window_skipped:
            # Don't advance the checkpoint chain on a window that skipped
            # updates: rollback must land BEFORE the divergence started.
            # That veto extends to any snapshot still queued from a timed
            # save INSIDE this window (async saves capture state at enqueue
            # time, but a bad window disqualifies the whole window).
            self.ckpt.veto_pending()
            saved = False
        else:
            saved = self._maybe_save(step, at_eval_boundary=at_boundary)
        if at_boundary or saved:
            # Exclude the eval/summary/save work above from the next
            # training window (the boundary tick_to already closed this
            # window at the completion barrier; a mid-window timed save
            # drops the partial window — steps AND time — so the next
            # boundary doesn't attribute full-window steps to partial time).
            timer.mark(step)
            self._reset_window_obs(step)

    def _maybe_save(self, step: int, force: bool = False, at_eval_boundary: bool = True) -> bool:
        from distributed_tensorflow_tpu.train.checkpoint import coordinated_maybe_save

        return coordinated_maybe_save(
            self.ckpt, step, self._state_dict(), self.is_chief,
            force=force, at_boundary=at_eval_boundary,
        )
