from distributed_tensorflow_tpu.train.checkpoint import (  # noqa: F401
    CheckpointManager,
    export_inference_bundle,
    load_inference_bundle,
)
from distributed_tensorflow_tpu.train.loop import MnistTrainer  # noqa: F401
