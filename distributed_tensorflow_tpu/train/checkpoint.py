"""Checkpointing + model export.

Replaces the reference's three formats (SURVEY §5.4):
  (a) explicit final ``tf.train.Saver`` ckpt (``demo1/train.py:144,165``)
      → Orbax save at the end of training;
  (b) ``Supervisor`` timed autosave every 600 s to ``logdir`` with
      auto-restore-on-restart (``demo2/train.py:166-176``)
      → :class:`CheckpointManager` with a wall-clock save gate,
      ``restore_latest``, and a zero-stall snapshot→write→finalize save
      pipeline (background device→host fetch, per-process sharded writes,
      deferred multi-process commit — DESIGN.md §9);
  (c) frozen-GraphDef + labels export
      (``retrain1/retrain.py:470-475``)
      → :func:`export_inference_bundle`: a msgpack params pytree + labels
      file. "Freezing" is meaningless under JAX — params are already data
      and the apply fn is retraced/jitted at load time.
"""

from __future__ import annotations

import glob
import json
import os
import queue
import shutil
import threading
import time
import zipfile
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from flax import serialization

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.logging import get_logger
from distributed_tensorflow_tpu.utils.retry import retry_call

log = get_logger(__name__)

# Orbax I/O retry envelope: transient filesystem/NFS hiccups get a couple of
# quick retries; deterministic failures (corrupt step, template mismatch)
# raise OSError subclasses rarely and fall through to the walk-back loop.
_IO_ATTEMPTS = 3
_IO_BASE_DELAY = 0.1
_IO_MAX_DELAY = 2.0


def _cross_process_sharded(x) -> bool:
    """A leaf that no single process can fetch: sharded (not replicated)
    across a multi-process mesh. ``device_get`` on such arrays raises;
    Orbax saves/restores them natively (each process handles its shards)."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _savable(state: Any) -> Any:
    """numpy for fetchable leaves (replicated / single-process — the fast,
    simple case); cross-process-sharded jax.Arrays pass through for Orbax's
    distributed array handler. Only the synchronous (``ckpt_async=0``)
    single-process path still uses this — the async pipeline fetches through
    :class:`_SnapshotJob` units instead."""
    return jax.tree_util.tree_map(
        lambda x: x if _cross_process_sharded(x) else np.asarray(jax.device_get(x)),
        state,
    )


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, including the ml_dtypes extension types (bfloat16
    et al.) that plain numpy only knows once ml_dtypes is imported."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _path_tokens(path) -> list[dict]:
    """JSON-serializable form of a tree_flatten_with_path key path: dict keys
    as {"k": name}, sequence/index keys as {"i": idx} — enough to rebuild a
    plain dict/list nesting for template-free restores."""
    toks: list[dict] = []
    for k in path:
        if hasattr(k, "key"):
            toks.append({"k": str(k.key)})
        elif hasattr(k, "idx"):
            toks.append({"i": int(k.idx)})
        elif hasattr(k, "name"):
            toks.append({"k": str(k.name)})
        else:
            toks.append({"k": str(k)})
    return toks


def _index_bounds(index, shape) -> list[list[int]]:
    """A shard's index (tuple of slices) as [[start, stop], ...] per dim."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


# ---------------------------------------------------------------------------
# Snapshot pipeline — zero-stall autosave.
#
# Three stages (DESIGN.md §9):
#   snapshot  — an on-device defensive copy of the state tree (fresh buffers,
#       so later DONATING train dispatches can never invalidate what the
#       background thread reads), then a chunked, double-buffered device→host
#       fetch on the snapshot worker thread (chunk i+1's transfer is started
#       before chunk i is materialized);
#   write     — single-process: the Orbax save (itself async). Multi-process:
#       each process writes ONLY the bytes it owns (replica-0 addressable
#       shards; replicated/host leaves are the chief's alone) into a
#       per-process npz + manifest under the step dir — NO collectives ever
#       run on this thread;
#   finalize  — multi-process durability is deferred to an explicit drain
#       point on the MAIN thread (the next eval boundary, or a forced save):
#       processes allgather their local write status and the chief then
#       writes the COMMIT marker. Restores ignore uncommitted steps. Keeping
#       every collective on the main thread is what makes async multi-process
#       saves deadlock-free against ``broadcast_one_to_all`` (the hazard that
#       previously forced multi-process saves fully synchronous).
# ---------------------------------------------------------------------------

_JOB_PENDING, _JOB_DONE, _JOB_FAILED, _JOB_CANCELLED = 0, 1, 2, 3


class _Unit:
    """One fetchable piece of a snapshot: a whole leaf, or one addressable
    shard of a cross-process-sharded leaf."""

    __slots__ = ("data", "host", "nbytes", "keystr", "tokens", "shape", "dtype", "index")

    def __init__(self, data, keystr, tokens, shape, dtype, index):
        self.data = data          # device array / shard data / numpy
        self.host: np.ndarray | None = None
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.nbytes = int(np.prod(self.shape or (1,))) * _np_dtype(self.dtype).itemsize
        self.keystr = keystr
        self.tokens = tokens
        self.index = index        # None = full leaf; else [[lo, hi], ...]


class _SnapshotJob:
    def __init__(self, step: int, units: list[_Unit], treedef, multi: bool):
        self.step = step
        self.units = units
        self.treedef = treedef    # single-process: rebuild the Orbax tree
        self.multi = multi
        self.done = threading.Event()
        self.status = _JOB_PENDING
        self.error: Exception | None = None
        self.cancelled = False
        self.writing = False      # set just before the write stage (veto point)
        self.warned = False       # skip-with-warning rate limit
        self.held = False         # test seam: park the job until released/vetoed


def _assemble_full(elist, load) -> np.ndarray:
    """Reassemble a full array from its covering replica-0 shard entries.
    Entries store BLOCK shapes; the global extent per dim is the max stop
    over the covering shards."""
    _, e0 = elist[0]
    global_shape = [
        max(e["index"][d][1] for _, e in elist) for d in range(len(e0["index"]))
    ]
    value = np.empty(global_shape, _np_dtype(e0["dtype"]))
    for p, e in elist:
        sl = tuple(slice(lo, hi) for lo, hi in e["index"])
        value[sl] = load(p, e)
    return value


class _ShardStore:
    """Per-process sharded checkpoint files + commit markers (the
    multi-process backend). Layout under ``directory/<step>/``:

      shard_p<K>.npz     process K's bytes (uint8-viewed leaf/shard blocks)
      manifest_p<K>.json what lives in K's npz (path, shape, dtype, index)
      COMMIT.json        written by the CHIEF at finalize — only committed
                         steps exist as far as restores are concerned

    Readable from any process count (a single-process tool can reassemble a
    multi-process save — ``demo2/test.py``'s restore-latest fallback)."""

    COMMIT = "COMMIT.json"

    def __init__(self, directory: str):
        self.directory = directory

    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    @staticmethod
    def is_sharded_dir(step_dir: str) -> bool:
        return bool(
            os.path.exists(os.path.join(step_dir, _ShardStore.COMMIT))
            or glob.glob(os.path.join(step_dir, "manifest_p*.json"))
        )

    @staticmethod
    def is_committed(step_dir: str) -> bool:
        return os.path.exists(os.path.join(step_dir, _ShardStore.COMMIT))

    def write_local(self, step: int, units: list[_Unit]) -> None:
        """Write THIS process's shard file + manifest (atomic renames, no
        coordination — the commit marker is finalize's job)."""
        p = jax.process_index()
        d = self.step_dir(step)
        os.makedirs(d, exist_ok=True)
        arrays: dict[str, np.ndarray] = {}
        entries = []
        for i, u in enumerate(units):
            key = f"a{i}"
            arrays[key] = np.ascontiguousarray(u.host).reshape(-1).view(np.uint8)
            entries.append(
                {
                    "key": key,
                    "path": u.keystr,
                    "tokens": u.tokens,
                    "shape": list(u.shape),
                    "dtype": u.dtype,
                    "index": u.index,
                }
            )
        shard_path = os.path.join(d, f"shard_p{p}.npz")
        tmp = shard_path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp, shard_path)
        man = {
            "format": "dtt.sharded.v1",
            "process": p,
            "process_count": jax.process_count(),
            "entries": entries,
        }
        man_path = os.path.join(d, f"manifest_p{p}.json")
        tmp = man_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(man, fh)
        os.replace(tmp, man_path)

    def commit(self, step: int) -> None:
        d = self.step_dir(step)
        tmp = os.path.join(d, self.COMMIT + ".tmp")
        with open(tmp, "w") as fh:
            json.dump({"step": step, "process_count": jax.process_count()}, fh)
        os.replace(tmp, os.path.join(d, self.COMMIT))

    def committed_steps(self) -> list[int]:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            d = os.path.join(self.directory, n)
            if n.isdigit() and self.is_sharded_dir(d) and self.is_committed(d):
                out.append(int(n))
        return sorted(out)

    def retain(self, max_to_keep: int) -> None:
        """Chief-only retention over committed sharded steps (Orbax-format
        steps keep Orbax's own retention)."""
        if max_to_keep is None or max_to_keep <= 0:
            return
        for step in self.committed_steps()[:-max_to_keep]:
            shutil.rmtree(self.step_dir(step), ignore_errors=True)

    def abandon(self, step: int) -> None:
        d = self.step_dir(step)
        if os.path.isdir(d) and not self.is_committed(d):
            shutil.rmtree(d, ignore_errors=True)

    # -- read ---------------------------------------------------------------

    def _load_entries(self, step: int):
        """Returns (entries_by_path, load_fn, closer): every manifest entry of
        the committed save, keyed by leaf keystr."""
        d = self.step_dir(step)
        with open(os.path.join(d, self.COMMIT)) as fh:
            commit = json.load(fh)
        nproc = int(commit["process_count"])
        by_path: dict[str, list] = {}
        for p in range(nproc):
            with open(os.path.join(d, f"manifest_p{p}.json")) as fh:
                man = json.load(fh)
            for e in man["entries"]:
                by_path.setdefault(e["path"], []).append((p, e))
        npz_cache: dict[int, Any] = {}

        def load(p: int, entry: dict) -> np.ndarray:
            npz = npz_cache.get(p)
            if npz is None:
                npz = npz_cache[p] = np.load(os.path.join(d, f"shard_p{p}.npz"))
            raw = npz[entry["key"]]
            return raw.view(_np_dtype(entry["dtype"])).reshape(entry["shape"])

        def close() -> None:
            for npz in npz_cache.values():
                npz.close()

        return by_path, load, close

    def read(self, step: int, template: Any | None):
        """Template-driven restore (cross-process-sharded template leaves come
        back as sharded jax.Arrays, everything else numpy), or template-free
        reassembly into plain dicts/lists when ``template`` is None."""
        by_path, load, close = self._load_entries(step)
        try:
            if template is None:
                return self._assemble_raw(by_path, load)

            def restore_leaf(path, leaf):
                ks = jax.tree_util.keystr(path)
                elist = by_path.get(ks)
                if not elist:
                    raise OSError(f"checkpoint step {step} is missing leaf {ks}")
                if _cross_process_sharded(leaf):
                    shape = tuple(leaf.shape)
                    sharding = leaf.sharding
                    idx_map = sharding.devices_indices_map(shape)
                    by_bounds = {
                        tuple(map(tuple, e["index"])): (p, e)
                        for p, e in elist
                        if e["index"] is not None
                    }
                    arrays = []
                    for dev in sharding.addressable_devices:
                        bounds = tuple(
                            map(tuple, _index_bounds(idx_map[dev], shape))
                        )
                        if bounds not in by_bounds:
                            raise OSError(
                                f"checkpoint step {step}: no shard covering "
                                f"{bounds} of {ks} (saved with a different "
                                "mesh/process layout?)"
                            )
                        p, e = by_bounds[bounds]
                        arrays.append(jax.device_put(load(p, e), dev))
                    return jax.make_array_from_single_device_arrays(
                        shape, sharding, arrays
                    )
                full = [pe for pe in elist if pe[1]["index"] is None]
                if full:
                    value = load(*full[0])
                else:
                    # A host/replicated template leaf reading a save whose
                    # leaf was cross-process sharded (e.g. a single-process
                    # tool restoring a distributed run): reassemble the full
                    # array from the covering replica-0 shards.
                    value = _assemble_full(elist, load)
                if hasattr(leaf, "shape") and tuple(np.shape(leaf)) != tuple(value.shape):
                    raise OSError(
                        f"checkpoint step {step}: shape mismatch for {ks}: "
                        f"saved {value.shape}, template {np.shape(leaf)}"
                    )
                return value

            return jax.tree_util.tree_map_with_path(restore_leaf, template)
        finally:
            close()

    def _assemble_raw(self, by_path, load):
        out: Any = {}
        for ks, elist in by_path.items():
            full = [pe for pe in elist if pe[1]["index"] is None]
            value = load(*full[0]) if full else _assemble_full(elist, load)
            node = out
            toks = elist[0][1]["tokens"]
            for i, t in enumerate(toks):
                last = i == len(toks) - 1
                if "k" in t:
                    key = t["k"]
                    if last:
                        node[key] = value
                    else:
                        node = node.setdefault(
                            key, [] if "i" in toks[i + 1] else {}
                        )
                else:
                    idx = t["i"]
                    while len(node) <= idx:
                        node.append(None)
                    if last:
                        node[idx] = value
                    else:
                        if node[idx] is None:
                            node[idx] = [] if "i" in toks[i + 1] else {}
                        node = node[idx]
        return out


# ---------------------------------------------------------------------------
# Public sharded-format surface (serve/deploy/ and tools).
#
# The deploy watcher consumes checkpoints through these three functions
# instead of re-parsing ``manifest_p*.json`` privately: the manifest walk,
# the commit-marker rule (only COMMIT.json makes a step visible) and the
# shard reassembly live in ONE place — :class:`_ShardStore` — no matter
# whether the reader is a restore, a watcher, or a CLI.
# ---------------------------------------------------------------------------


def list_committed_steps(directory: str) -> list:
    """Committed sharded-format steps under ``directory``, ascending.

    A step counts only once its ``COMMIT.json`` marker exists (written by
    the chief via atomic rename at finalize) — torn or uncommitted step
    dirs (process killed mid-write, finalize never ran) are invisible,
    exactly like restores treat them. Orbax-format steps are NOT listed:
    this is the watch surface for the per-process shard+manifest format.
    """
    return _ShardStore(directory).committed_steps()


def read_step(directory: str, step: int, template: Any | None = None):
    """Read one COMMITTED sharded-format step.

    ``template=None`` reassembles plain dicts/lists with numpy leaves
    (cross-process-sharded leaves are stitched back to full arrays);
    with a template, leaves restore against it like ``restore_latest``.
    Raises ``OSError`` for an uncommitted/missing step or a committed dir
    whose shard/manifest files are missing or torn (the caller — e.g. the
    deploy watcher — skips and walks on, like restores walk back).
    """
    store = _ShardStore(directory)
    d = store.step_dir(step)
    if not store.is_committed(d):
        raise OSError(
            f"checkpoint step {step} in {directory} is not committed "
            f"(no {_ShardStore.COMMIT})"
        )
    try:
        return store.read(step, template)
    except (OSError, ValueError, KeyError, json.JSONDecodeError,
            zipfile.BadZipFile) as e:
        raise OSError(
            f"checkpoint step {step} in {directory} is committed but "
            f"unreadable: {type(e).__name__}: {e}"
        ) from e


def write_committed_step(directory: str, step: int, tree: Any) -> str:
    """Publish ``tree`` as ONE committed sharded-format step from this
    process (shard_p<K>.npz + manifest_p<K>.json + COMMIT.json, all via
    atomic renames). This is the single-process producer half of the
    watch surface: trainers publish a weight tree for serving without a
    multi-process finalize (whose commit is collective), and tests/bench
    drop checkpoints the deploy watcher can adopt. Returns the step dir.

    Host-fetchable leaves only (replicated or single-process); a
    cross-process-sharded leaf cannot be published from one process.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    units = []
    for path, leaf in flat:
        arr = np.asarray(jax.device_get(leaf))
        unit = _Unit(
            None, jax.tree_util.keystr(path), _path_tokens(path),
            arr.shape, arr.dtype, None,
        )
        unit.host = arr
        units.append(unit)
    store = _ShardStore(directory)
    store.write_local(step, units)
    faults.maybe_fail("ckpt_publish", f"step {step}")
    store.commit(step)
    return store.step_dir(step)


class CheckpointManager:
    """Supervisor-parity manager (timed autosave, keep-N, restore-latest)
    with a zero-stall save pipeline: timed autosaves cost the training
    thread only an on-device copy dispatch + job enqueue (``stall_seconds``
    measures exactly that blocked time); the device→host fetch and the disk
    write run on a background snapshot thread. Single-process saves land in
    Orbax format; multi-process saves are per-process sharded files whose
    collective finalize is deferred to :meth:`finalize_pending` (called by
    ``coordinated_maybe_save`` at eval boundaries). Forced saves
    (final/emergency) remain fully synchronous and durable on return."""

    def __init__(
        self,
        directory: str,
        save_interval_secs: float = 600.0,
        max_to_keep: int = 5,
        async_snapshot: bool = True,
        snapshot_chunk_mb: int = 64,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )
        self.save_interval_secs = save_interval_secs
        self.max_to_keep = max_to_keep
        self.async_snapshot = async_snapshot
        self.snapshot_chunk_mb = max(1, int(snapshot_chunk_mb))
        self._last_save = time.time()
        self.stall_seconds = 0.0  # main-thread time blocked inside save paths
        self._store = _ShardStore(self.directory)
        self._lock = threading.Lock()
        self._worker: threading.Thread | None = None
        self._queue: "queue.Queue[_SnapshotJob | None]" = queue.Queue()
        self._jobs: list[_SnapshotJob] = []  # issued, not yet retired/finalized
        self._issued: set[int] = set()
        self._hold_next_snapshot = False  # test seam: park the next job

    # -- gate ----------------------------------------------------------------

    def should_save(self, force: bool = False) -> bool:
        """The timed-autosave gate, side-effect free (multi-process callers
        broadcast the chief's answer so every process enters the save
        together)."""
        return force or time.time() - self._last_save >= self.save_interval_secs

    def mark_saved(self) -> None:
        self._last_save = time.time()

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if ``save_interval_secs`` elapsed since the last save (the
        Supervisor's timed-autosave behavior) or if forced (final save —
        which also WAITS, so the artifact exists before the process exits).
        A timed gate firing while the previous save is still in flight skips
        with a warning instead of blocking the training thread."""
        if not self.should_save(force):
            return False
        if self.save(step, state, wait=force, skip_if_busy=not force):
            self.mark_saved()
            return True
        return False

    # -- save ----------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        wait: bool = False,
        skip_if_busy: bool = False,
    ) -> bool:
        """Issue a save of ``state`` at ``step``. Returns True when the save
        is satisfied (issued, or the step already exists on disk); False only
        on the ``skip_if_busy`` path — the timed-gate caller's non-blocking
        skip while the previous save is still in flight.

        Async (default): the training thread pays an on-device snapshot copy
        dispatch + enqueue; fetch/write happen on the snapshot thread.
        ``wait=True`` (final/emergency saves) drains everything — the
        artifact is durable (and in multi-process runs committed) on return.
        """
        t0 = time.perf_counter()
        try:
            with obs.span("checkpoint_save", step=int(step), wait=bool(wait)):
                multi = jax.process_count() > 1
                busy = self._busy()
                if busy and skip_if_busy:
                    self._warn_busy(step)
                    obs.trace_event("ckpt_skip_busy", step=int(step))
                    return False
                # Duplicate-step guard WITHOUT draining (the old
                # unconditional wait_until_finished here head-of-line-blocked
                # the caller for the whole previous write even when this
                # guard made the call a no-op): hit when a finished job
                # restarts (restore to step N, zero-iteration loop, forced
                # re-save of N) or when the timed gate fires on the very last
                # step before the final save.
                if step in self._issued or step in self._all_steps():
                    if wait:
                        self._drain_jobs()
                        if multi:
                            self.finalize_pending(block=True)
                        else:
                            self._mngr.wait_until_finished()
                    return True
                if busy:
                    # Direct (non-gate) callers keep strict ordering: drain
                    # the previous save before issuing the next.
                    self._drain_jobs()
                    if multi:
                        self.finalize_pending(block=True)
                self._issued.add(step)
                if not multi and not self.async_snapshot and not wait:
                    # ckpt_async=0: the pre-pipeline behavior — synchronous
                    # device→host fetch on this thread, Orbax's own
                    # background write overlapping training.
                    self._orbax_write(step, _savable(state))
                    return True
                job = self._make_job(step, state, multi)
                self._enqueue(job)
                if wait or not self.async_snapshot:
                    self._drain_jobs()
                    if job.error is not None:
                        raise job.error
                    if multi:
                        self.finalize_pending(block=True)
                    else:
                        self._mngr.wait_until_finished()
                return True
        finally:
            self.stall_seconds += time.perf_counter() - t0

    def _make_job(self, step: int, state: Any, multi: bool) -> _SnapshotJob:
        """Snapshot stage, main-thread half: an on-device defensive copy of
        every device leaf (fresh buffers — a later dispatch that DONATES the
        originals cannot invalidate them), then the fetch plan: which pieces
        THIS process owns. All of it is asynchronous dispatch + bookkeeping;
        no device→host bytes move here."""
        from distributed_tensorflow_tpu.parallel import data_parallel as dp

        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        leaves = [leaf for _, leaf in flat]
        dev_idx = [i for i, x in enumerate(leaves) if isinstance(x, jax.Array)]
        if dev_idx:
            copies = dp.device_copy([leaves[i] for i in dev_idx])
            for i, c in zip(dev_idx, copies):
                leaves[i] = c
        chief = (not multi) or jax.process_index() == 0
        units: list[_Unit] = []
        for (path, _), leaf in zip(flat, leaves):
            ks = jax.tree_util.keystr(path)
            toks = _path_tokens(path)
            if _cross_process_sharded(leaf):
                global_shape = tuple(leaf.shape)
                for s in leaf.addressable_shards:
                    if s.replica_id != 0:
                        continue  # exactly one process writes each shard
                    # Unit shape = the BLOCK's shape (that is what gets
                    # written); index records its place in the global array.
                    units.append(
                        _Unit(
                            s.data, ks, toks, tuple(s.data.shape), leaf.dtype,
                            _index_bounds(s.index, global_shape),
                        )
                    )
            elif chief:
                # Replicated / host leaves: the chief alone writes them —
                # non-chief processes move zero bytes for these.
                data = leaf if isinstance(leaf, jax.Array) else np.array(leaf, copy=True)
                units.append(
                    _Unit(data, ks, toks, np.shape(data), np.asarray(data).dtype
                          if not isinstance(data, jax.Array) else data.dtype, None)
                )
        return _SnapshotJob(step, units, treedef, multi)

    def _enqueue(self, job: _SnapshotJob) -> None:
        with self._lock:
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._worker_loop, name="ckpt-snapshot", daemon=True
                )
                self._worker.start()
            if self._hold_next_snapshot:
                job.held = True
                self._hold_next_snapshot = False
            self._jobs.append(job)
        self._queue.put(job)

    # -- snapshot worker -----------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._run_job(job)
            except Exception as e:  # noqa: BLE001 — surfaced via job.error
                job.error = e
                job.status = _JOB_FAILED
                log.error(
                    "background checkpoint save of step %d failed: %s: %s",
                    job.step, type(e).__name__, e,
                )
            finally:
                job.done.set()

    def _run_job(self, job: _SnapshotJob) -> None:
        deadline = time.monotonic() + 60.0
        while job.held and not job.cancelled and time.monotonic() < deadline:
            time.sleep(0.005)
        if job.cancelled:
            job.status = _JOB_CANCELLED
            log.warning("checkpoint snapshot of step %d cancelled (vetoed)", job.step)
            return
        if not self._fetch(job):
            job.status = _JOB_CANCELLED
            log.warning(
                "checkpoint snapshot of step %d cancelled mid-fetch (vetoed)",
                job.step,
            )
            return
        job.writing = True

        def _write() -> None:
            # Fault site ``ckpt_save`` fires BEFORE the write — models a
            # transient I/O error the backoff retry recovers from, now on
            # the background path.
            faults.maybe_fail("ckpt_save", f"step {job.step}")
            if job.multi:
                self._store.write_local(job.step, job.units)
            else:
                # Serialize against Orbax's own async machinery: this wait is
                # on the WORKER thread, so the training thread never pays it.
                self._mngr.wait_until_finished()
                host_leaves = [u.host for u in job.units]
                self._mngr.save(
                    job.step,
                    args=ocp.args.StandardSave(job.treedef.unflatten(host_leaves)),
                )

        retry_call(
            _write,
            attempts=_IO_ATTEMPTS,
            base_delay=_IO_BASE_DELAY,
            max_delay=_IO_MAX_DELAY,
            description=f"checkpoint save step {job.step}",
        )
        job.status = _JOB_DONE

    def _fetch(self, job: _SnapshotJob) -> bool:
        """Chunked, double-buffered device→host copy: units are grouped into
        ~``snapshot_chunk_mb`` chunks; chunk i+1's async transfer is started
        before chunk i is materialized, so transfer overlaps materialization.
        Returns False when the job is vetoed between chunks."""
        chunk_bytes = self.snapshot_chunk_mb * (1 << 20)
        groups: list[list[_Unit]] = []
        cur: list[_Unit] = []
        cur_bytes = 0
        for u in job.units:
            if cur and cur_bytes + u.nbytes > chunk_bytes:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(u)
            cur_bytes += u.nbytes
        if cur:
            groups.append(cur)

        def start(group: list[_Unit]) -> None:
            for u in group:
                if isinstance(u.data, jax.Array):
                    try:
                        u.data.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — best-effort prefetch
                        pass

        if groups:
            start(groups[0])
        for gi, group in enumerate(groups):
            if job.cancelled:
                return False
            if gi + 1 < len(groups):
                start(groups[gi + 1])
            for u in group:
                u.host = np.asarray(u.data)  # waits on the in-flight transfer
                u.data = None  # release the device buffer reference early
        return True

    # -- bookkeeping ---------------------------------------------------------

    def _busy(self) -> bool:
        with self._lock:
            if jax.process_count() > 1:
                # Pending = unfinalized — identical across processes (save
                # decisions are broadcast), so the skip decision is symmetric.
                return bool(self._jobs)
            self._jobs = [j for j in self._jobs if not j.done.is_set()]
            return bool(self._jobs)

    def _warn_busy(self, step: int) -> None:
        with self._lock:
            job = self._jobs[0] if self._jobs else None
        if job is not None and not job.warned:
            job.warned = True
            log.warning(
                "skipping timed checkpoint of step %d: save of step %d still "
                "in flight (will retry at the next gate)", step, job.step,
            )

    def _drain_jobs(self) -> None:
        """Join every issued snapshot job (worker-side work only — NO
        collectives, safe from any caller/thread)."""
        for j in list(self._jobs):
            j.done.wait()
        if jax.process_count() == 1:
            with self._lock:
                self._jobs = [j for j in self._jobs if not j.done.is_set()]

    def veto_pending(self) -> int:
        """Cancel snapshot jobs that have not reached the write stage — the
        bad-eval-window suppression and rollback paths use this so a queued
        snapshot from inside a diverging window never advances the
        checkpoint chain. Jobs already writing are left alone (their data was
        captured at enqueue time). Returns the number cancelled."""
        n = 0
        with self._lock:
            for j in self._jobs:
                if not j.done.is_set() and not j.writing:
                    j.cancelled = True
                    n += 1
        if n:
            log.warning("vetoed %d queued checkpoint snapshot(s)", n)
            obs.trace_event("ckpt_veto", cancelled=n)
        return n

    def finalize_pending(self, block: bool = False) -> None:
        """Deferred multi-process finalize — the ONLY collective piece of the
        async save, and it runs on the caller's (main) thread at explicit
        drain points: eval boundaries, forced saves, restores. Processes
        allgather their local write status; when all are done the chief
        writes the COMMIT marker (then a named barrier makes the commit
        visible to everyone before any process may act on it). A failed or
        vetoed shard write on ANY process abandons the step everywhere.
        Single-process: no-op."""
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        while True:
            with self._lock:
                job = self._jobs[0] if self._jobs else None
            if job is None:
                return
            if block:
                job.done.wait()
            status = job.status if job.done.is_set() else _JOB_PENDING
            code = {_JOB_PENDING: 0, _JOB_DONE: 1}.get(status, 2)
            gathered = multihost_utils.process_allgather(
                np.asarray([code], np.int32)
            )
            codes = set(int(x) for x in np.ravel(gathered))
            if 0 in codes:
                if not block:
                    return  # not everyone is done — try again next boundary
                time.sleep(0.2)
                continue
            with self._lock:
                self._jobs.remove(job)
            if 2 in codes:
                log.warning(
                    "abandoning uncommitted checkpoint step %d (a process "
                    "failed or vetoed its shard write)", job.step,
                )
                if jax.process_index() == 0:
                    self._store.abandon(job.step)
                self._issued.discard(job.step)
            else:
                if jax.process_index() == 0:
                    self._store.commit(job.step)
                    self._store.retain(self.max_to_keep)
                multihost_utils.sync_global_devices(f"dtt_ckpt_commit_{job.step}")
                log.info("finalized checkpoint step %d (deferred commit)", job.step)

    def _orbax_write(self, step: int, data: Any) -> None:
        def _write() -> None:
            faults.maybe_fail("ckpt_save", f"step {step}")
            self._mngr.save(step, args=ocp.args.StandardSave(data))

        retry_call(
            _write,
            attempts=_IO_ATTEMPTS,
            base_delay=_IO_BASE_DELAY,
            max_delay=_IO_MAX_DELAY,
            description=f"checkpoint save step {step}",
        )

    # -- introspection -------------------------------------------------------

    def wait_until_finished(self) -> None:
        """Drain the snapshot worker and Orbax's background write. NO
        collectives — committing multi-process saves is
        :meth:`finalize_pending`'s job."""
        self._drain_jobs()
        self._mngr.wait_until_finished()

    def _all_steps(self) -> list[int]:
        """Steps visible on disk: Orbax-format step dirs plus COMMITTED
        sharded-format step dirs (an uncommitted sharded dir is an in-flight
        or abandoned save, never a restorable step)."""
        steps = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for n in names:
            if not n.isdigit():
                continue
            d = os.path.join(self.directory, n)
            if not os.path.isdir(d):
                continue
            if _ShardStore.is_sharded_dir(d) and not _ShardStore.is_committed(d):
                continue
            steps.add(int(n))
        return sorted(steps)

    def all_steps(self) -> list[int]:
        self.wait_until_finished()
        return self._all_steps()

    def latest_step(self) -> int | None:
        self.wait_until_finished()  # include any in-flight async save
        steps = self._all_steps()
        return steps[-1] if steps else None

    # -- restore -------------------------------------------------------------

    def _read_step(self, step: int, template: Any | None, raw: bool = False):
        """Format-probing per-step reader: sharded-format steps go through
        the shard store (works from any process count); Orbax-format steps
        through Orbax."""
        d = os.path.join(self.directory, str(step))
        if _ShardStore.is_sharded_dir(d):
            return self._store.read(step, None if raw else template)
        if raw:
            # Explicit StandardRestore: a FRESH manager (demo2/test.py's
            # restore-latest fallback) has no handler registry from a prior
            # save in this process, and a bare restore() then raises instead
            # of inferring — with args it reads the tree as numpy directly.
            return self._mngr.restore(step, args=ocp.args.StandardRestore())
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if _cross_process_sharded(x)
            else np.asarray(jax.device_get(x)),
            template,
        )
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def _walk_back_restore(self, restore_fn):
        """Restore the newest READABLE step, newest→oldest: a truncated or
        corrupt latest checkpoint (process killed mid-write, bad disk) is
        skipped with a warning instead of blocking every restart while older
        good checkpoints sit on disk. Returns (step, state) or None (no
        steps, or none readable — init fresh beats crash-looping). Drains
        the snapshot worker first, and in multi-process runs finalizes any
        pending save (all processes restore at the same program point, so
        the collective is symmetric — rollback's drain-or-finalize)."""
        self.wait_until_finished()
        self.finalize_pending(block=True)
        steps = sorted(self._all_steps(), reverse=True)
        skipped: list[int] = []
        for step in steps:
            def _read(step=step):
                faults.maybe_fail("ckpt_restore", f"step {step}")
                return restore_fn(step)

            try:
                state = retry_call(
                    _read,
                    attempts=2,
                    base_delay=_IO_BASE_DELAY,
                    max_delay=_IO_MAX_DELAY,
                    description=f"checkpoint restore step {step}",
                )
            except Exception as e:
                log.warning(
                    "checkpoint step %d unreadable (%s: %s) — walking back",
                    step, type(e).__name__, e,
                )
                skipped.append(step)
                continue
            if skipped:
                log.warning(
                    "restored step %d after skipping corrupt/partial "
                    "checkpoint step(s) %s", step, skipped,
                )
            return step, state
        if skipped:
            log.error("no readable checkpoint (skipped %s) — starting fresh", skipped)
        return None

    def restore_latest_raw(self):
        """Restore the newest readable ckpt without a structure template
        (numpy leaves, dict/list nesting); returns (step, state) or None."""
        return self._walk_back_restore(
            lambda step: self._read_step(step, None, raw=True)
        )

    def restore_latest(self, template: Any):
        """Returns (step, state) restored from the newest readable ckpt, or
        None — mirrors Supervisor init-or-restore (``demo2/train.py:176``),
        plus the corrupt-checkpoint walk-back (see
        :meth:`_walk_back_restore`). Cross-process-sharded template leaves
        restore as sharded jax.Arrays (each process reads its own shards);
        everything else as numpy."""
        return self._walk_back_restore(lambda step: self._read_step(step, template))

    def close(self) -> None:
        self._drain_jobs()
        with self._lock:
            worker, self._worker = self._worker, None
        if worker is not None:
            self._queue.put(None)
            worker.join(timeout=30)
        self._mngr.close()


def restore_replicated(mngr: CheckpointManager, template: Any, mesh):
    """Restore the newest checkpoint and place it mesh-replicated, leaf
    dtypes taken from ``template`` (the live train state). Returns
    (step, state) or None. Shared by the MNIST and retrain trainers."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.parallel import data_parallel as dp

    restored = mngr.restore_latest(template)
    if restored is None:
        return None
    step, state = restored
    placed = jax.tree_util.tree_map(
        lambda a, b: dp.replicate(jnp.asarray(b, a.dtype), mesh)
        if hasattr(a, "dtype")
        else b,
        template,
        state,
    )
    return step, placed


def coordinated_maybe_save(
    mngr: CheckpointManager,
    step: int,
    state: Any,
    is_chief: bool,
    force: bool = False,
    at_boundary: bool = True,
) -> bool:
    """Timed autosave, multi-process safe — the one save gate both trainers
    use. Saves are group-wide when ``jax.process_count() > 1`` (each process
    writes its own shards, and the chief's timed-gate decision is broadcast
    at eval boundaries so every process issues the save together), but the
    save itself is ASYNC: the per-process shard writes run on background
    threads with zero collectives, and the collective finalize is DEFERRED
    to this function's next boundary call (``finalize_pending`` — main
    thread, so it can never deadlock against the gate broadcast the way a
    background finalize barrier did). Forced saves (final/emergency) stay
    synchronous and committed on return. Single process keeps exact
    Supervisor semantics (chief-only, per-call gate)."""
    if jax.process_count() == 1:
        return mngr.maybe_save(step, state, force=force) if is_chief else False
    if not (at_boundary or force):
        return False
    # Deferred-finalize drain point: commit (or abandon) any async save whose
    # shard writes have finished, BEFORE possibly issuing the next one.
    mngr.finalize_pending(block=force)
    from jax.experimental import multihost_utils

    want = mngr.should_save(force)
    if not bool(multihost_utils.broadcast_one_to_all(np.asarray(want))):
        return False
    # skip_if_busy is symmetric across processes: "busy" means an
    # unfinalized pending save, and the pending set is identical everywhere
    # (save decisions are broadcast), so either every process saves or every
    # process skips. wait=force: forced saves drain + finalize inline.
    if mngr.save(step, state, wait=force, skip_if_busy=not force):
        mngr.mark_saved()
        return True
    return False


# ---------------------------------------------------------------------------
# Inference bundle (frozen-graph export parity).
# ---------------------------------------------------------------------------


def export_inference_bundle(
    path: str,
    params: Any,
    labels: list[str] | None = None,
    labels_path: str | None = None,
    metadata: dict | None = None,
) -> None:
    """Write params as a msgpack state-dict (+ optional labels txt, one class
    per line — ``retrain1/retrain.py:474-475`` parity) and a small JSON header."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    state = serialization.to_state_dict(jax.device_get(params))
    blob = serialization.msgpack_serialize(state)
    header = json.dumps({"format": "dtf_tpu.params.v1", **(metadata or {})}).encode()
    with open(path, "wb") as fh:
        fh.write(len(header).to_bytes(8, "little"))
        fh.write(header)
        fh.write(blob)
    if labels is not None and labels_path is not None:
        with open(labels_path, "w") as fh:
            fh.write("\n".join(labels) + "\n")


def load_inference_bundle(path: str, template: Any | None = None):
    """Returns (params_state_dict_or_restored_pytree, metadata)."""
    with open(path, "rb") as fh:
        hlen = int.from_bytes(fh.read(8), "little")
        metadata = json.loads(fh.read(hlen).decode())
        state = serialization.msgpack_restore(fh.read())
    if template is not None:
        state = serialization.from_state_dict(template, state)
    return state, metadata


def load_lm_bundle(path: str, fallback_shapes: dict | None = None):
    """Restore a TransformerLM bundle: (cfg, params, metadata).

    One loader for every LM CLI (generate/eval): prefers the config embedded
    in the bundle metadata, falls back to ``fallback_shapes`` (CLI flags) for
    pre-metadata bundles; unstacks pp bundles; rejects tp/ep bundles (their
    param factorizations — separate q/k/v, expert-stacked MLPs — don't load
    into the plain decoder). Raises ValueError on tp/ep.
    """
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    state, meta = load_inference_bundle(path)
    if meta.get("parallelism") in ("tp", "ep", "3d", "sp_tp"):
        raise ValueError(
            f"{meta['parallelism']} bundles use a different param "
            "factorization (separate q/k/v for tp/3d/sp_tp, expert-stacked "
            "MoE MLPs for ep) that the plain decoder cannot load — retrain "
            "with dp/fsdp/sp/pp"
        )
    if "stages" in state:
        from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
            unstack_stage_params,
        )

        state = unstack_stage_params(state)
    fb = fallback_shapes or {}
    shape_meta = meta.get("config") or {}

    def dim(name, default):
        return int(shape_meta.get(name, fb.get(name, default)))

    cfg = TransformerConfig(
        vocab_size=dim("vocab_size", 256),
        d_model=dim("d_model", 128),
        num_heads=dim("num_heads", 4),
        # 0/absent = MHA (pre-GQA bundles carry no num_kv_heads key).
        num_kv_heads=dim("num_kv_heads", 0) or None,
        attention_window=dim("attention_window", 0) or None,
        # 1/absent = biased Dense layers (pre-r5 bundles carry no use_bias
        # key and were always trained with biases on the CLI path).
        use_bias=bool(dim("use_bias", 1)),
        # 0/absent = learned position table (pre-RoPE bundles). theta is a
        # FLOAT (dim() would truncate it) — a non-default rotation base must
        # survive the round trip or inference silently rotates q/k by the
        # wrong angles.
        position="rope" if dim("rope", 0) else "learned",
        rope_theta=float(shape_meta.get("rope_theta", fb.get("rope_theta", 10000.0))),
        num_layers=dim("num_layers", 4),
        d_ff=dim("d_ff", 512),
        max_seq_len=dim("max_seq_len", 128),
        # Quantized bundles (tools/quantize_lm.py): the mode must ride the
        # metadata so the init template below grows the matching
        # kernel_q/scale leaf structure — from_state_dict restores by
        # structure, and int leaves cannot load into a float-kernel tree.
        weight_dtype=(shape_meta.get("weight_dtype")
                      or fb.get("weight_dtype") or None),
        quant_group_size=dim("quant_group_size", 0),
        # KV ACTIVATION format (orthogonal to weight_dtype): a bundle
        # exported with tools/quantize_lm.py --kv_dtype int8 serves
        # quantize-on-write int8 KV pages by default; --kv_dtype/
        # --kv_cache_dtype at serve time still override.
        kv_cache_dtype=(shape_meta.get("kv_cache_dtype")
                        or fb.get("kv_cache_dtype") or None),
        compute_dtype=jnp.bfloat16
        if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    template = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    params = serialization.from_state_dict(template, state)
    return cfg, params, meta


def load_vit_bundle(path: str):
    """Restore a ViT classifier bundle from ``tools/train_image_classifier``:
    (cfg, params, metadata). Shape config, class labels, and the TRAINING
    compute dtype all come from the embedded metadata (so a CPU-trained f32
    bundle classifies in f32 even on a TPU host, and vice versa)."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.vit import ViT, ViTConfig

    state, meta = load_inference_bundle(path)
    shape_meta = meta.get("config")
    if not shape_meta or not meta.get("labels"):
        raise ValueError(
            f"{path} lacks embedded config/labels — train it with "
            "tools/train_image_classifier.py"
        )
    dtype_name = meta.get("compute_dtype", "float32")
    cfg = ViTConfig(
        **{k: int(v) for k, v in shape_meta.items()},
        compute_dtype=jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32,
    )
    template = ViT(cfg).init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32),
    )["params"]
    params = serialization.from_state_dict(template, state)
    return cfg, params, meta


def load_labels(path: str) -> list[str]:
    with open(path) as fh:
        return [ln.rstrip("\n") for ln in fh if ln.strip()]


# ---------------------------------------------------------------------------
# Frozen StableHLO export — the closest TPU-native analog of the reference's
# ``graph_util.convert_variables_to_constants`` (`retrain1/retrain.py:470-473`):
# one self-contained compiled-program artifact with the weights baked in as
# constants, loadable and runnable without the model's Python code.
# ---------------------------------------------------------------------------


def export_frozen_stablehlo(
    path: str,
    fn,
    example_args: tuple,
    metadata: dict | None = None,
    platforms: tuple[str, ...] = ("cpu", "tpu"),
    polymorphic_batch: bool = True,
) -> None:
    """Serialize ``jit(fn)`` (params already closed over / baked in) traced at
    ``example_args``'s shapes to a portable StableHLO artifact via
    ``jax.export``. Multi-platform by default so an artifact exported on TPU
    still runs on CPU (and vice versa). With ``polymorphic_batch`` the leading
    axis of every non-scalar arg becomes one shared symbolic dim, so the
    loaded program accepts any batch size (the frozen .pb took any batch too)."""
    from jax import export as jax_export

    batch_dim = jax_export.symbolic_shape("b")[0] if polymorphic_batch else None

    def spec(a):
        shape = np.shape(a)
        if batch_dim is not None and len(shape) >= 1:
            shape = (batch_dim,) + tuple(shape[1:])
        return jax.ShapeDtypeStruct(shape, np.asarray(a).dtype)

    specs = jax.tree_util.tree_map(spec, example_args)
    exported = jax_export.export(jax.jit(fn), platforms=list(platforms))(*specs)
    blob = exported.serialize()
    header = json.dumps(
        {"format": "dtf_tpu.stablehlo.v1", "platforms": list(platforms), **(metadata or {})}
    ).encode()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(len(header).to_bytes(8, "little"))
        fh.write(header)
        fh.write(bytes(blob))


def export_frozen_classifier(
    path: str,
    apply_fn,
    params: Any,
    input_shape: tuple[int, ...],
    metadata: dict | None = None,
) -> None:
    """The one frozen-classifier export shape every CLI shares: bake
    ``softmax(apply_fn({'params': params}, x))`` into a polymorphic-batch
    StableHLO artifact, traced at ``(1, *input_shape)`` float32 input."""
    params = jax.device_get(params)

    def frozen_probs(x):
        return jax.nn.softmax(apply_fn({"params": params}, x), -1)

    export_frozen_stablehlo(
        path,
        frozen_probs,
        (np.zeros((1, *input_shape), np.float32),),
        metadata=metadata,
    )


def load_frozen_stablehlo(path: str):
    """Returns (callable, metadata): the deserialized exported program. The
    callable jit-executes on the current default backend — no model code or
    params needed, exactly like loading the reference's frozen ``.pb``."""
    from jax import export as jax_export

    with open(path, "rb") as fh:
        hlen = int.from_bytes(fh.read(8), "little")
        metadata = json.loads(fh.read(hlen).decode())
        blob = fh.read()
    exported = jax_export.deserialize(bytearray(blob))
    return exported.call, metadata
