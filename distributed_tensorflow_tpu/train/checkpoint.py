"""Checkpointing + model export.

Replaces the reference's three formats (SURVEY §5.4):
  (a) explicit final ``tf.train.Saver`` ckpt (``demo1/train.py:144,165``)
      → Orbax save at the end of training;
  (b) ``Supervisor`` timed autosave every 600 s to ``logdir`` with
      auto-restore-on-restart (``demo2/train.py:166-176``)
      → :class:`CheckpointManager` with a wall-clock save gate and
      ``restore_latest``;
  (c) frozen-GraphDef + labels export
      (``retrain1/retrain.py:470-475``)
      → :func:`export_inference_bundle`: a msgpack params pytree + labels
      file. "Freezing" is meaningless under JAX — params are already data
      and the apply fn is retraced/jitted at load time.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from flax import serialization

from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.logging import get_logger
from distributed_tensorflow_tpu.utils.retry import retry_call

log = get_logger(__name__)

# Orbax I/O retry envelope: transient filesystem/NFS hiccups get a couple of
# quick retries; deterministic failures (corrupt step, template mismatch)
# raise OSError subclasses rarely and fall through to the walk-back loop.
_IO_ATTEMPTS = 3
_IO_BASE_DELAY = 0.1
_IO_MAX_DELAY = 2.0


def _cross_process_sharded(x) -> bool:
    """A leaf that no single process can fetch: sharded (not replicated)
    across a multi-process mesh. ``device_get`` on such arrays raises;
    Orbax saves/restores them natively (each process handles its shards)."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _savable(state: Any) -> Any:
    """numpy for fetchable leaves (replicated / single-process — the fast,
    simple case); cross-process-sharded jax.Arrays pass through for Orbax's
    distributed array handler."""
    return jax.tree_util.tree_map(
        lambda x: x if _cross_process_sharded(x) else np.asarray(jax.device_get(x)),
        state,
    )


class CheckpointManager:
    """Orbax-backed manager with Supervisor-parity semantics: timed autosave
    (default 600 s, ``demo2/train.py:172``), keep-N, restore-latest-on-start."""

    def __init__(
        self,
        directory: str,
        save_interval_secs: float = 600.0,
        max_to_keep: int = 5,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )
        self.save_interval_secs = save_interval_secs
        self._last_save = time.time()

    def should_save(self, force: bool = False) -> bool:
        """The timed-autosave gate, side-effect free (multi-process callers
        broadcast the chief's answer so every process enters the collective
        Orbax save together)."""
        return force or time.time() - self._last_save >= self.save_interval_secs

    def mark_saved(self) -> None:
        self._last_save = time.time()

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if ``save_interval_secs`` elapsed since the last save (the
        Supervisor's timed-autosave behavior) or if forced (final save —
        which also WAITS, so the artifact exists before the process exits)."""
        if not self.should_save(force):
            return False
        self.save(step, state, wait=force)
        self.mark_saved()
        return True

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Async by default: the device→host fetch is synchronous (cheap),
        the disk write overlaps training — the Supervisor also autosaved
        from a background thread (demo2/train.py:166-172). The previous
        in-flight save is drained first; ``wait=True`` (final saves) blocks
        until the artifact is durable."""
        # Drain the previous in-flight save BEFORE the duplicate-step guard:
        # an async save of step N not yet visible in latest_step() would
        # otherwise slip past the guard and raise StepAlreadyExistsError on
        # the forced re-save of N (and in multi-process runs, one process
        # erroring out of the collective save deadlocks the others).
        self._mngr.wait_until_finished()
        if not wait and any(
            _cross_process_sharded(leaf)
            for leaf in jax.tree_util.tree_leaves(state)
        ):
            # Cross-process-sharded leaves pass to Orbax as live jax.Arrays
            # (no host copy in _savable) — an async write would race the
            # training loop's next in-place update of those buffers.
            wait = True
        if self._mngr.latest_step() == step:
            # Re-saving an existing step raises StepAlreadyExistsError in
            # Orbax — hit when a finished job restarts (restore to step N,
            # zero-iteration loop, final forced save of N) or when the timed
            # gate fires on the very last step before the final save.
            return
        data = _savable(state)

        def _write() -> None:
            # Fault site ``ckpt_save`` fires BEFORE the Orbax call — models a
            # transient I/O error the backoff retry recovers from.
            faults.maybe_fail("ckpt_save", f"step {step}")
            self._mngr.save(step, args=ocp.args.StandardSave(data))

        retry_call(
            _write,
            attempts=_IO_ATTEMPTS,
            base_delay=_IO_BASE_DELAY,
            max_delay=_IO_MAX_DELAY,
            description=f"checkpoint save step {step}",
        )
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> int | None:
        self._mngr.wait_until_finished()  # include any in-flight async save
        return self._mngr.latest_step()

    def _walk_back_restore(self, restore_fn):
        """Restore the newest READABLE step, newest→oldest: a truncated or
        corrupt latest checkpoint (process killed mid-write, bad disk) is
        skipped with a warning instead of blocking every restart while older
        good checkpoints sit on disk. Returns (step, state) or None (no
        steps, or none readable — init fresh beats crash-looping)."""
        self._mngr.wait_until_finished()
        steps = sorted(self._mngr.all_steps(), reverse=True)
        skipped: list[int] = []
        for step in steps:
            def _read(step=step):
                faults.maybe_fail("ckpt_restore", f"step {step}")
                return restore_fn(step)

            try:
                state = retry_call(
                    _read,
                    attempts=2,
                    base_delay=_IO_BASE_DELAY,
                    max_delay=_IO_MAX_DELAY,
                    description=f"checkpoint restore step {step}",
                )
            except Exception as e:
                log.warning(
                    "checkpoint step %d unreadable (%s: %s) — walking back",
                    step, type(e).__name__, e,
                )
                skipped.append(step)
                continue
            if skipped:
                log.warning(
                    "restored step %d after skipping corrupt/partial "
                    "checkpoint step(s) %s", step, skipped,
                )
            return step, state
        if skipped:
            log.error("no readable checkpoint (skipped %s) — starting fresh", skipped)
        return None

    def restore_latest_raw(self):
        """Restore the newest readable ckpt without a structure template
        (numpy leaves); returns (step, state) or None."""
        return self._walk_back_restore(lambda step: self._mngr.restore(step))

    def restore_latest(self, template: Any):
        """Returns (step, state) restored from the newest readable ckpt, or
        None — mirrors Supervisor init-or-restore (``demo2/train.py:176``),
        plus the corrupt-checkpoint walk-back (see
        :meth:`_walk_back_restore`). Cross-process-sharded template leaves
        restore as sharded jax.Arrays (each process reads its own shards);
        everything else as numpy."""
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if _cross_process_sharded(x)
            else np.asarray(jax.device_get(x)),
            template,
        )
        return self._walk_back_restore(
            lambda step: self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        )

    def close(self) -> None:
        self._mngr.close()


def restore_replicated(mngr: CheckpointManager, template: Any, mesh):
    """Restore the newest checkpoint and place it mesh-replicated, leaf
    dtypes taken from ``template`` (the live train state). Returns
    (step, state) or None. Shared by the MNIST and retrain trainers."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.parallel import data_parallel as dp

    restored = mngr.restore_latest(template)
    if restored is None:
        return None
    step, state = restored
    placed = jax.tree_util.tree_map(
        lambda a, b: dp.replicate(jnp.asarray(b, a.dtype), mesh)
        if hasattr(a, "dtype")
        else b,
        template,
        state,
    )
    return step, placed


def coordinated_maybe_save(
    mngr: CheckpointManager,
    step: int,
    state: Any,
    is_chief: bool,
    force: bool = False,
    at_boundary: bool = True,
) -> bool:
    """Timed autosave, multi-process safe — the one save gate both trainers
    use. Orbax saves are COLLECTIVE when ``jax.process_count() > 1``: a
    chief-only save desynchronizes the process group (observed gloo
    size-mismatch crash), so the chief's timed-gate decision is broadcast at
    eval boundaries and every process enters the save together. Single
    process keeps exact Supervisor semantics (chief-only, per-call gate)."""
    if jax.process_count() == 1:
        return mngr.maybe_save(step, state, force=force) if is_chief else False
    if not (at_boundary or force):
        return False
    from jax.experimental import multihost_utils

    want = mngr.should_save(force)
    if bool(multihost_utils.broadcast_one_to_all(np.asarray(want))):
        # wait=True: multi-process saves stay SYNCHRONOUS. The async
        # finalize barrier runs on a background thread over the same
        # coordination service the main threads use for the broadcast above;
        # interleaving the two deadlocks the group (observed in the
        # 2-process demo2 test). Async autosave applies single-process.
        mngr.save(step, state, wait=True)
        mngr.mark_saved()
        return True
    return False


# ---------------------------------------------------------------------------
# Inference bundle (frozen-graph export parity).
# ---------------------------------------------------------------------------


def export_inference_bundle(
    path: str,
    params: Any,
    labels: list[str] | None = None,
    labels_path: str | None = None,
    metadata: dict | None = None,
) -> None:
    """Write params as a msgpack state-dict (+ optional labels txt, one class
    per line — ``retrain1/retrain.py:474-475`` parity) and a small JSON header."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    state = serialization.to_state_dict(jax.device_get(params))
    blob = serialization.msgpack_serialize(state)
    header = json.dumps({"format": "dtf_tpu.params.v1", **(metadata or {})}).encode()
    with open(path, "wb") as fh:
        fh.write(len(header).to_bytes(8, "little"))
        fh.write(header)
        fh.write(blob)
    if labels is not None and labels_path is not None:
        with open(labels_path, "w") as fh:
            fh.write("\n".join(labels) + "\n")


def load_inference_bundle(path: str, template: Any | None = None):
    """Returns (params_state_dict_or_restored_pytree, metadata)."""
    with open(path, "rb") as fh:
        hlen = int.from_bytes(fh.read(8), "little")
        metadata = json.loads(fh.read(hlen).decode())
        state = serialization.msgpack_restore(fh.read())
    if template is not None:
        state = serialization.from_state_dict(template, state)
    return state, metadata


def load_lm_bundle(path: str, fallback_shapes: dict | None = None):
    """Restore a TransformerLM bundle: (cfg, params, metadata).

    One loader for every LM CLI (generate/eval): prefers the config embedded
    in the bundle metadata, falls back to ``fallback_shapes`` (CLI flags) for
    pre-metadata bundles; unstacks pp bundles; rejects tp/ep bundles (their
    param factorizations — separate q/k/v, expert-stacked MLPs — don't load
    into the plain decoder). Raises ValueError on tp/ep.
    """
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    state, meta = load_inference_bundle(path)
    if meta.get("parallelism") in ("tp", "ep", "3d", "sp_tp"):
        raise ValueError(
            f"{meta['parallelism']} bundles use a different param "
            "factorization (separate q/k/v for tp/3d/sp_tp, expert-stacked "
            "MoE MLPs for ep) that the plain decoder cannot load — retrain "
            "with dp/fsdp/sp/pp"
        )
    if "stages" in state:
        from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
            unstack_stage_params,
        )

        state = unstack_stage_params(state)
    fb = fallback_shapes or {}
    shape_meta = meta.get("config") or {}

    def dim(name, default):
        return int(shape_meta.get(name, fb.get(name, default)))

    cfg = TransformerConfig(
        vocab_size=dim("vocab_size", 256),
        d_model=dim("d_model", 128),
        num_heads=dim("num_heads", 4),
        # 0/absent = MHA (pre-GQA bundles carry no num_kv_heads key).
        num_kv_heads=dim("num_kv_heads", 0) or None,
        attention_window=dim("attention_window", 0) or None,
        # 1/absent = biased Dense layers (pre-r5 bundles carry no use_bias
        # key and were always trained with biases on the CLI path).
        use_bias=bool(dim("use_bias", 1)),
        # 0/absent = learned position table (pre-RoPE bundles). theta is a
        # FLOAT (dim() would truncate it) — a non-default rotation base must
        # survive the round trip or inference silently rotates q/k by the
        # wrong angles.
        position="rope" if dim("rope", 0) else "learned",
        rope_theta=float(shape_meta.get("rope_theta", fb.get("rope_theta", 10000.0))),
        num_layers=dim("num_layers", 4),
        d_ff=dim("d_ff", 512),
        max_seq_len=dim("max_seq_len", 128),
        compute_dtype=jnp.bfloat16
        if jax.default_backend() == "tpu"
        else jnp.float32,
    )
    template = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    params = serialization.from_state_dict(template, state)
    return cfg, params, meta


def load_vit_bundle(path: str):
    """Restore a ViT classifier bundle from ``tools/train_image_classifier``:
    (cfg, params, metadata). Shape config, class labels, and the TRAINING
    compute dtype all come from the embedded metadata (so a CPU-trained f32
    bundle classifies in f32 even on a TPU host, and vice versa)."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.models.vit import ViT, ViTConfig

    state, meta = load_inference_bundle(path)
    shape_meta = meta.get("config")
    if not shape_meta or not meta.get("labels"):
        raise ValueError(
            f"{path} lacks embedded config/labels — train it with "
            "tools/train_image_classifier.py"
        )
    dtype_name = meta.get("compute_dtype", "float32")
    cfg = ViTConfig(
        **{k: int(v) for k, v in shape_meta.items()},
        compute_dtype=jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32,
    )
    template = ViT(cfg).init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32),
    )["params"]
    params = serialization.from_state_dict(template, state)
    return cfg, params, meta


def load_labels(path: str) -> list[str]:
    with open(path) as fh:
        return [ln.rstrip("\n") for ln in fh if ln.strip()]


# ---------------------------------------------------------------------------
# Frozen StableHLO export — the closest TPU-native analog of the reference's
# ``graph_util.convert_variables_to_constants`` (`retrain1/retrain.py:470-473`):
# one self-contained compiled-program artifact with the weights baked in as
# constants, loadable and runnable without the model's Python code.
# ---------------------------------------------------------------------------


def export_frozen_stablehlo(
    path: str,
    fn,
    example_args: tuple,
    metadata: dict | None = None,
    platforms: tuple[str, ...] = ("cpu", "tpu"),
    polymorphic_batch: bool = True,
) -> None:
    """Serialize ``jit(fn)`` (params already closed over / baked in) traced at
    ``example_args``'s shapes to a portable StableHLO artifact via
    ``jax.export``. Multi-platform by default so an artifact exported on TPU
    still runs on CPU (and vice versa). With ``polymorphic_batch`` the leading
    axis of every non-scalar arg becomes one shared symbolic dim, so the
    loaded program accepts any batch size (the frozen .pb took any batch too)."""
    from jax import export as jax_export

    batch_dim = jax_export.symbolic_shape("b")[0] if polymorphic_batch else None

    def spec(a):
        shape = np.shape(a)
        if batch_dim is not None and len(shape) >= 1:
            shape = (batch_dim,) + tuple(shape[1:])
        return jax.ShapeDtypeStruct(shape, np.asarray(a).dtype)

    specs = jax.tree_util.tree_map(spec, example_args)
    exported = jax_export.export(jax.jit(fn), platforms=list(platforms))(*specs)
    blob = exported.serialize()
    header = json.dumps(
        {"format": "dtf_tpu.stablehlo.v1", "platforms": list(platforms), **(metadata or {})}
    ).encode()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(len(header).to_bytes(8, "little"))
        fh.write(header)
        fh.write(bytes(blob))


def export_frozen_classifier(
    path: str,
    apply_fn,
    params: Any,
    input_shape: tuple[int, ...],
    metadata: dict | None = None,
) -> None:
    """The one frozen-classifier export shape every CLI shares: bake
    ``softmax(apply_fn({'params': params}, x))`` into a polymorphic-batch
    StableHLO artifact, traced at ``(1, *input_shape)`` float32 input."""
    params = jax.device_get(params)

    def frozen_probs(x):
        return jax.nn.softmax(apply_fn({"params": params}, x), -1)

    export_frozen_stablehlo(
        path,
        frozen_probs,
        (np.zeros((1, *input_shape), np.float32),),
        metadata=metadata,
    )


def load_frozen_stablehlo(path: str):
    """Returns (callable, metadata): the deserialized exported program. The
    callable jit-executes on the current default backend — no model code or
    params needed, exactly like loading the reference's frozen ``.pb``."""
    from jax import export as jax_export

    with open(path, "rb") as fh:
        hlen = int.from_bytes(fh.read(8), "little")
        metadata = json.loads(fh.read(hlen).decode())
        blob = fh.read()
    exported = jax_export.deserialize(bytearray(blob))
    return exported.call, metadata
