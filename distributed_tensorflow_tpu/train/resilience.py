"""Preemption-safe shutdown + divergence rollback control flow.

The reference's ``tf.train.Supervisor`` gave demo2 crash-resume only by
accident of its timed autosave (``demo2/train.py:166-176``) — a SIGTERM still
lost up to ``save_model_secs`` of work. Here preemption is first-class:

* :class:`PreemptionGuard` installs SIGTERM/SIGINT handlers that set a flag;
  the training loop polls it at step boundaries and raises
  :class:`Preempted`, which the trainer catches to run a coordinated
  emergency save and return cleanly — restart then resumes through the
  existing ``restore_replicated`` path.
* Multi-process: the flag is agreed on at eval boundaries via
  ``process_allgather`` (any preempted process preempts the group), so every
  process enters the collective emergency save together — a unilateral exit
  would wedge the others in their next collective.
* :class:`RollbackRequested` is the non-finite guard's escalation: after K
  consecutive eval windows containing skipped (non-finite) steps, the loop
  rolls back to the last good checkpoint instead of burning compute on a
  diverged run.
* Async-save integration: the emergency checkpoint is a FORCED save, which
  drains the in-flight background snapshot first (one durable, committed
  artifact on exit); rollback's restore likewise drains-or-finalizes pending
  saves, and bad eval windows veto queued snapshots
  (``CheckpointManager.veto_pending``) so the chain never advances into the
  divergence.
* Flight recording: both failure paths call :func:`dump_flight_record`, which
  writes the obs ring buffer (last-N spans/events — checkpoint saves, the
  emergency-shutdown span, rollback events) as JSONL into the configured
  ``--obs_dir``, so a preempted or diverged run ships its own timeline.

Signal handlers only install in the main thread (Python restriction); off
the main thread the guard degrades to poll-only (tests can still call
``request()``).
"""

from __future__ import annotations

import signal
import threading

import jax
import numpy as np

from distributed_tensorflow_tpu.obs import recorder as _flight
from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


def dump_flight_record(reason: str) -> str | None:
    """Dump the process flight recorder into the configured obs dump dir
    (``obs.set_dump_dir`` / ``--obs_dir``). No-op (returns None) when no dump
    dir is set; best-effort on I/O errors — this runs on failure paths."""
    path = _flight.dump_to_dir(reason)
    if path is not None:
        log.info("flight record (%s) -> %s", reason, path)
    return path


class Preempted(Exception):
    """Raised by the training loop at a step boundary after a preemption
    request; carries the host step the loop stopped at."""

    def __init__(self, step: int):
        super().__init__(f"preemption requested at step {step}")
        self.step = step


class RollbackRequested(Exception):
    """Raised at an eval boundary when the non-finite-window budget is
    exhausted; the trainer restores the last good checkpoint and resumes."""

    def __init__(self, step: int, bad_windows: int):
        super().__init__(
            f"{bad_windows} consecutive eval windows with non-finite steps "
            f"at step {step}"
        )
        self.step = step
        self.bad_windows = bad_windows


class PreemptionGuard:
    """Latches SIGTERM/SIGINT into a flag the training loop polls.

    Use as a context manager around the training loop so the previous
    handlers are always restored (pytest owns SIGINT, for one)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._flag = False
        self._prev: dict[int, object] = {}
        self._installed = False

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # poll-only mode
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- state -------------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        # Handler body stays async-signal-minimal: set the flag, nothing else.
        self._flag = True

    def request(self) -> None:
        """Programmatic preemption (fault injection / tests) — identical to a
        signal arriving."""
        self._flag = True

    @property
    def requested(self) -> bool:
        return self._flag

    def should_exit(self, at_boundary: bool) -> bool:
        """The loop's per-step-boundary poll. Single process: any boundary.
        Multi-process: only eval boundaries, where all processes reach the
        same program point and can agree collectively (any-of semantics)."""
        if jax.process_count() == 1:
            return self._flag
        if not at_boundary:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._flag], dtype=np.bool_)
        )
        agreed = bool(np.any(flags))
        if agreed and not self._flag:
            log.info("peer process requested preemption — joining emergency save")
        return agreed
