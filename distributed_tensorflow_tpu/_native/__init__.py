"""ctypes loader for the native runtime library (``native.cc``).

Build-on-first-import with an atomic rename (safe under concurrent pytest
workers / multi-process training); every entry point has a pure-Python
fallback, so the framework degrades gracefully when no C++ toolchain is
available (``lib() is None`` then).

Set ``DTF_NATIVE=0`` to force the Python fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native.cc")
_SO = os.path.join(os.path.dirname(__file__), "libdtfnative.so")

_lib: ctypes.CDLL | None = None
_load_attempted = False


def _build() -> bool:
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(_SO))
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)  # atomic: concurrent builders race benignly
        return True
    except (OSError, subprocess.SubprocessError):
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def lib() -> ctypes.CDLL | None:
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("DTF_NATIVE", "1") == "0":
        return None
    try:
        stale = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
    except OSError:
        stale = False  # can't stat — use whatever .so exists
    if stale and not _build():
        return None
    if not os.path.exists(_SO):
        return None
    try:
        cdll = ctypes.CDLL(_SO)
    except OSError:
        return None
    cdll.dtf_crc32c.restype = ctypes.c_uint32
    cdll.dtf_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.dtf_crc32c_sw.restype = ctypes.c_uint32
    cdll.dtf_crc32c_sw.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.dtf_masked_crc32c.restype = ctypes.c_uint32
    cdll.dtf_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.dtf_frame_record.restype = ctypes.c_size_t
    cdll.dtf_frame_record.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
    ]
    cdll.dtf_parse_csv_floats.restype = ctypes.c_int64
    cdll.dtf_parse_csv_floats.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    cdll.dtf_format_csv_floats.restype = ctypes.c_int64
    cdll.dtf_format_csv_floats.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    _lib = cdll
    return _lib


# ---------------------------------------------------------------------------
# Typed wrappers (native when available, else None — callers keep their
# pure-Python implementations as the fallback branch).
# ---------------------------------------------------------------------------


def masked_crc32c(data: bytes) -> int | None:
    l = lib()
    if l is None:
        return None
    return l.dtf_masked_crc32c(data, len(data))


def frame_record(data: bytes) -> bytes | None:
    """One TFRecord frame: u64le(len) crc data crc."""
    l = lib()
    if l is None:
        return None
    out = ctypes.create_string_buffer(len(data) + 16)
    n = l.dtf_frame_record(data, len(data), out)
    return out.raw[:n]


def parse_csv_floats(text: bytes, expected_size: int | None = None) -> np.ndarray | None:
    """Parse comma-separated floats. Returns None if the native lib is
    unavailable. Raises ValueError on malformed input (parity with the Python
    codec's corruption signal)."""
    l = lib()
    if l is None:
        return None
    cap = expected_size if expected_size else max(1, (len(text) + 1) // 2)
    out = np.empty(cap, dtype=np.float32)
    n = l.dtf_parse_csv_floats(text, len(text), out.ctypes.data_as(ctypes.c_void_p), cap)
    if n < 0:
        raise ValueError("malformed csv float data")
    return out[:n].copy() if n != cap else out


def format_csv_floats(values: np.ndarray) -> bytes | None:
    l = lib()
    if l is None:
        return None
    arr = np.ascontiguousarray(values, dtype=np.float32).reshape(-1)
    cap = 24 * max(1, arr.size)
    out = ctypes.create_string_buffer(cap)
    n = l.dtf_format_csv_floats(
        arr.ctypes.data_as(ctypes.c_void_p), arr.size, out, cap
    )
    if n < 0:
        raise RuntimeError("csv format buffer too small")  # cap=24/float can't happen
    return out.raw[:n]
