// Native runtime kernels for distributed_tensorflow_tpu.
//
// The reference delegates its host-side runtime to the TensorFlow 1.x C++
// core: the TFRecord/CRC32C event record writer behind tf.summary.FileWriter
// (demo1/train.py:151) and the per-step bottleneck cache-file text codec that
// dominates the retrain hot loop (retrain1/retrain.py:430-438 reads + parses
// comma-separated float files every training step). This library is the
// TPU-build's native equivalent of those subsystems, exposed over a plain C
// ABI and loaded from Python via ctypes (no pybind11 in this environment).
//
// Pure-Python fallbacks exist for every entry point; byte-format differences
// between the two CSV writers are allowed, but parsed float32 values are
// guaranteed identical (both emit shortest-round-trip decimals).

#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli). Slice-by-8 table software path + SSE4.2 hardware path,
// selected once at runtime.
// ---------------------------------------------------------------------------

uint32_t g_table[8][256];
bool g_tables_ready = false;

void build_tables() {
  const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int k = 0; k < 8; ++k) crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    g_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      g_table[s][i] = (g_table[s - 1][i] >> 8) ^ g_table[0][g_table[s - 1][i] & 0xFF];
  g_tables_ready = true;
}

uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
  if (!g_tables_ready) build_tables();
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    word ^= crc;  // little-endian: low 4 bytes absorb the running crc
    crc = g_table[7][word & 0xFF] ^ g_table[6][(word >> 8) & 0xFF] ^
          g_table[5][(word >> 16) & 0xFF] ^ g_table[4][(word >> 24) & 0xFF] ^
          g_table[3][(word >> 32) & 0xFF] ^ g_table[2][(word >> 40) & 0xFF] ^
          g_table[1][(word >> 48) & 0xFF] ^ g_table[0][(word >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = g_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    c = __builtin_ia32_crc32di(c, word);
    p += 8;
    n -= 8;
  }
  while (n--) c = __builtin_ia32_crc32qi(static_cast<uint32_t>(c), *p++);
  return static_cast<uint32_t>(c);
}

uint32_t (*g_crc_impl)(uint32_t, const uint8_t*, size_t) = nullptr;

uint32_t crc32c_dispatch(uint32_t crc, const uint8_t* p, size_t n) {
  if (!g_crc_impl)
    g_crc_impl = __builtin_cpu_supports("sse4.2") ? crc32c_hw : crc32c_sw;
  return g_crc_impl(crc, p, n);
}

}  // namespace

extern "C" {

uint32_t dtf_crc32c(const uint8_t* data, size_t len) {
  return crc32c_dispatch(0xFFFFFFFFu, data, len) ^ 0xFFFFFFFFu;
}

// Software path, exported so parity tests can exercise it even on hosts whose
// dispatch always picks the SSE4.2 path.
uint32_t dtf_crc32c_sw(const uint8_t* data, size_t len) {
  return crc32c_sw(0xFFFFFFFFu, data, len) ^ 0xFFFFFFFFu;
}

// TFRecord masking (same scheme as TF's record writer).
uint32_t dtf_masked_crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = dtf_crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// Frame one TFRecord into `out` (caller provides len+16 bytes):
//   u64le(len) u32le(maskcrc(header)) data u32le(maskcrc(data))
// Returns bytes written (len + 16).
size_t dtf_frame_record(const uint8_t* data, size_t len, uint8_t* out) {
  uint64_t n = len;
  std::memcpy(out, &n, 8);
  uint32_t hcrc = dtf_masked_crc32c(out, 8);
  std::memcpy(out + 8, &hcrc, 4);
  std::memcpy(out + 12, data, len);
  uint32_t dcrc = dtf_masked_crc32c(data, len);
  std::memcpy(out + 12 + len, &dcrc, 4);
  return len + 16;
}

// Parse comma-separated floats from buf[0:len] into out (capacity cap).
// Returns the count parsed, or -1 on malformed input (bad char, empty field,
// trailing separator) — the Python caller maps -1 to the cache-corruption
// recovery path. Leading/trailing ASCII whitespace around fields is accepted.
int64_t dtf_parse_csv_floats(const char* buf, size_t len, float* out, size_t cap) {
  const char* p = buf;
  const char* end = buf + len;
  size_t count = 0;
  if (p == end) return 0;
  for (;;) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (p == end) return -1;  // empty field
    float value;
    auto res = std::from_chars(p, end, value);
    if (res.ec != std::errc()) return -1;
    p = res.ptr;
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (count >= cap) return -1;
    out[count++] = value;
    if (p == end) return static_cast<int64_t>(count);
    if (*p != ',') return -1;
    ++p;
  }
}

// Format floats as comma-separated shortest-round-trip decimals into out.
// Returns bytes written, or -1 if cap is too small (caller should size
// cap >= 16*n). No trailing NUL.
int64_t dtf_format_csv_floats(const float* vals, size_t n, char* out, size_t cap) {
  char* p = out;
  char* end = out + cap;
  for (size_t i = 0; i < n; ++i) {
    if (i) {
      if (p == end) return -1;
      *p++ = ',';
    }
    auto res = std::to_chars(p, end, vals[i]);
    if (res.ec != std::errc()) return -1;
    p = res.ptr;
  }
  return p - out;
}

}  // extern "C"
