"""Bundled-asset path resolution for the demo/retrain CLIs (C19 parity).

The reference's test CLIs hardcode relative ``imgs/`` and assume they are
run from the script's own directory (``demo1/test.py:187``); its sample
images ship in-repo so the CLIs run bare. Ours ship generated equivalents
(``tools/make_sample_assets.py``) — this helper lets a zero-arg run find
them from ANY working directory, while an explicit or existing path always
wins.
"""

from __future__ import annotations

import os

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


def dataclass_default(cls, name: str):
    """The declared default of dataclass field ``name`` — used by CLIs to
    tell 'flag left at its default' from 'explicitly passed' without
    duplicating the literal. Raises on default_factory fields (their
    ``f.default`` is the MISSING sentinel, which must not leak out as a
    comparison value)."""
    import dataclasses

    f = next(f for f in dataclasses.fields(cls) if f.name == name)
    if f.default is dataclasses.MISSING:
        raise ValueError(f"{cls.__name__}.{name} has no plain default")
    return f.default


def resolve_bundled_dir(
    path: str, script_file: str, bundled_name: str, default: str | None = None
) -> str:
    """Return ``path`` if it exists. The bundled fallback fires ONLY for the
    CLI's untouched default (``path == default``, or no default given): an
    explicitly passed path that is missing must surface as the caller's
    missing-dir error, never be silently redirected to sample data."""
    if os.path.isdir(path):
        return path
    if default is not None and path != default:
        return path
    bundled = os.path.join(
        os.path.dirname(os.path.abspath(script_file)), bundled_name
    )
    if os.path.isdir(bundled):
        log.info("%s not found; using bundled sample assets %s", path, bundled)
        return bundled
    return path
