"""Shared protocol-buffers wire-format primitives (no protobuf dependency).

One implementation for every proto producer/consumer in the framework: the
TensorBoard event writer (``utils/summary.py``) encodes Event/Summary protos,
and the GraphDef importer (``models/graphdef_import.py``) decodes the 2015
Inception ``.pb``. Wire types: 0 = varint, 1 = 64-bit, 2 = length-delimited,
5 = 32-bit.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator

__all__ = [
    "varint",
    "read_varint",
    "tag",
    "field_varint",
    "field_bytes",
    "field_float",
    "field_double",
    "field_packed_doubles",
    "iter_fields",
]


def varint(value: int) -> bytes:
    if value < 0:
        value &= 0xFFFFFFFFFFFFFFFF  # two's-complement 64-bit encoding
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def tag(field: int, wire_type: int) -> bytes:
    return varint((field << 3) | wire_type)


def field_varint(field: int, value: int) -> bytes:
    return tag(field, 0) + varint(value & 0xFFFFFFFFFFFFFFFF)


def field_bytes(field: int, value: bytes) -> bytes:
    return tag(field, 2) + varint(len(value)) + value


def field_float(field: int, value: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", value)


def field_double(field: int, value: float) -> bytes:
    return tag(field, 1) + struct.pack("<d", value)


def field_packed_doubles(field: int, values) -> bytes:
    return field_bytes(field, b"".join(struct.pack("<d", float(v)) for v in values))


def iter_fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over one message's bytes.

    Length-delimited values are returned as ``bytes`` slices; varints as int;
    fixed32/64 as raw 4/8-byte chunks.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = read_varint(buf, pos)
        elif wire == 2:
            length, pos = read_varint(buf, pos)
            if pos + length > n:
                raise ValueError(f"truncated field {field}")
            value = buf[pos : pos + length]
            pos += length
        elif wire == 5:
            if pos + 4 > n:
                raise ValueError(f"truncated field {field}")
            value = buf[pos : pos + 4]
            pos += 4
        elif wire == 1:
            if pos + 8 > n:
                raise ValueError(f"truncated field {field}")
            value = buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire} (field {field})")
        yield field, wire, value
