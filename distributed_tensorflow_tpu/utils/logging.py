"""Structured stdout logging (replaces the reference's bare ``print`` calls
and ``tf.logging.fatal``, e.g. ``retrain1/retrain.py:186-192,240``)."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"


def get_logger(
    name: str = "dtf_tpu", level: int = logging.INFO, stream=None
) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(stream if stream is not None else sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger
