"""PRNG key discipline.

The reference relies on TF 1.x implicit graph-level randomness (e.g.
``tf.truncated_normal`` in ``demo1/train.py:29``, random distortions in
``retrain1/retrain.py:137-165``). JAX requires explicit keys; these helpers
keep key handling uniform across the framework.
"""

from __future__ import annotations

import jax


class KeySeq:
    """Deterministic stream of PRNG keys: ``ks = KeySeq(0); k = ks.next()``."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)

    def next(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_n(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return subs


# Per-step key derivation happens ON-DEVICE inside the jitted train step
# (``data_parallel.build_train_step`` folds the replicated global_step into the
# base key), so keys stay a pure function of (base key, step) — stable under
# checkpoint/resume — without a host-side dispatch per step.
