"""PRNG key discipline.

The reference relies on TF 1.x implicit graph-level randomness (e.g.
``tf.truncated_normal`` in ``demo1/train.py:29``, random distortions in
``retrain1/retrain.py:137-165``). JAX requires explicit keys; these helpers
keep key handling uniform across the framework.
"""

from __future__ import annotations

import jax


class KeySeq:
    """Deterministic stream of PRNG keys: ``ks = KeySeq(0); k = ks.next()``."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)

    def next(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_n(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return subs


def fold_in_step(key: jax.Array, step: int) -> jax.Array:
    """Per-step key derivation — stable under checkpoint/resume (the key for
    step N is a pure function of (base key, N), so resuming mid-run replays
    identical dropout/augmentation randomness)."""
    return jax.random.fold_in(key, step)
