"""Self-contained TensorBoard event-file writer (no TF dependency).

TPU-native replacement for the reference's ``tf.summary.*`` + ``FileWriter``
observability layer (``demo1/train.py:15-24,143-146,151,157``;
``retrain1/retrain.py:248-258,420-421,440-446``). The reference delegates to
TF's C++ record writer; here the TFRecord framing (length + masked-CRC32C) and
the Event/Summary protobuf encoding are implemented directly so event files are
readable by any stock TensorBoard.

Wire formats implemented:
  * TFRecord: ``u64le(len) crc32c_masked(len_bytes) data crc32c_masked(data)``
  * ``Event``  proto: wall_time(1,double) step(2,int64) file_version(3,string)
    summary(5,message)
  * ``Summary`` proto: repeated value(1); ``Summary.Value``: tag(1,string)
    simple_value(2,float) histo(5,message)
  * ``HistogramProto``: min(1) max(2) num(3) sum(4) sum_squares(5)
    bucket_limit(6,packed double) bucket(7,packed double)
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np

from distributed_tensorflow_tpu import _native
from distributed_tensorflow_tpu.utils import protowire as _pw

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven, with the TFRecord masking scheme.
# ---------------------------------------------------------------------------

_CRC_TABLE: list[int] = []


def _build_crc_table() -> list[int]:
    poly = 0x82F63B78  # reflected Castagnoli polynomial
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


def crc32c(data: bytes) -> int:
    global _CRC_TABLE
    if not _CRC_TABLE:
        _CRC_TABLE = _build_crc_table()
    crc = 0xFFFFFFFF
    table = _CRC_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_MASK_DELTA = 0xA282EAD8


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Protobuf wire-format encoders — shared implementation in utils/protowire.py
# (also consumed by the GraphDef importer's reader side).
# ---------------------------------------------------------------------------

_varint = _pw.varint
_f_double = _pw.field_double
_f_float = _pw.field_float
_f_varint = _pw.field_varint
_f_bytes = _pw.field_bytes
_f_packed_doubles = _pw.field_packed_doubles


def encode_histogram(values: np.ndarray) -> bytes:
    """Encode a ``HistogramProto`` over ``values`` with TF-style exponential buckets."""
    flat = np.asarray(values, dtype=np.float64).ravel()
    if flat.size == 0:
        flat = np.zeros((1,), dtype=np.float64)
    # TF-compatible bucket boundaries: +/- 1e-12 * 1.1^k geometric series.
    limits = [1e-12]
    while limits[-1] < 1e20:
        limits.append(limits[-1] * 1.1)
    neg = [-x for x in reversed(limits)]
    bucket_limit = np.array(neg + limits + [np.finfo(np.float64).max])
    counts, _ = np.histogram(flat, bins=np.concatenate(([-np.inf], bucket_limit)))
    # Drop empty trailing/leading buckets for compactness (keep at least one).
    nz = np.nonzero(counts)[0]
    if nz.size:
        lo, hi = nz[0], nz[-1] + 1
        bucket_limit, counts = bucket_limit[lo:hi], counts[lo:hi]
    else:
        bucket_limit, counts = bucket_limit[:1], counts[:1]
    msg = b"".join(
        [
            _f_double(1, float(flat.min())),
            _f_double(2, float(flat.max())),
            _f_double(3, float(flat.size)),
            _f_double(4, float(flat.sum())),
            _f_double(5, float(np.square(flat).sum())),
            _f_packed_doubles(6, bucket_limit),
            _f_packed_doubles(7, counts.astype(np.float64)),
        ]
    )
    return msg


def encode_scalar_value(tag: str, value: float) -> bytes:
    return _f_bytes(1, _f_bytes(1, tag.encode()) + _f_float(2, float(value)))


def encode_histo_value(tag: str, values: np.ndarray) -> bytes:
    return _f_bytes(1, _f_bytes(1, tag.encode()) + _f_bytes(5, encode_histogram(values)))


def encode_event(
    wall_time: float,
    step: int | None = None,
    summary_values: bytes | None = None,
    file_version: str | None = None,
) -> bytes:
    msg = _f_double(1, wall_time)
    if step is not None:
        msg += _f_varint(2, int(step))
    if file_version is not None:
        msg += _f_bytes(3, file_version.encode())
    if summary_values:
        msg += _f_bytes(5, summary_values)
    return msg


def write_record(fh, data: bytes) -> None:
    framed = _native.frame_record(data)  # C++ CRC32C path (TF's record writer
    if framed is not None:               # is native too); None → no toolchain
        fh.write(framed)
        return
    header = struct.pack("<Q", len(data))
    fh.write(header)
    fh.write(struct.pack("<I", masked_crc32c(header)))
    fh.write(data)
    fh.write(struct.pack("<I", masked_crc32c(data)))


def read_records(path: str):
    """Yield raw record payloads from a TFRecord event file, verifying CRCs."""
    with open(path, "rb") as fh:
        while True:
            header = fh.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", fh.read(4))
            if masked_crc32c(header) != hcrc:
                raise IOError(f"corrupt record header in {path}")
            data = fh.read(length)
            (dcrc,) = struct.unpack("<I", fh.read(4))
            if masked_crc32c(data) != dcrc:
                raise IOError(f"corrupt record payload in {path}")
            yield data


# ---------------------------------------------------------------------------
# Public writer API.
# ---------------------------------------------------------------------------


class SummaryWriter:
    """TensorBoard event writer: ``add_scalar`` / ``add_histogram`` / ``flush``.

    Mirrors the role of ``tf.summary.FileWriter(logdir)`` in the reference
    (``demo1/train.py:151``). Thread-safe; writes are buffered and flushed
    explicitly or on close.
    """

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        self.logdir = logdir
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()),
            socket.gethostname(),
            filename_suffix,
        )
        self._path = os.path.join(logdir, fname)
        self._fh = open(self._path, "wb")
        self._lock = threading.Lock()
        write_record(self._fh, encode_event(time.time(), file_version="brain.Event:2"))

    @property
    def path(self) -> str:
        return self._path

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        with self._lock:
            write_record(
                self._fh, encode_event(time.time(), step, encode_scalar_value(tag, value))
            )

    def add_scalars(self, scalars: dict, step: int) -> None:
        values = b"".join(encode_scalar_value(t, v) for t, v in scalars.items())
        with self._lock:
            write_record(self._fh, encode_event(time.time(), step, values))

    def add_histogram(self, tag: str, values, step: int) -> None:
        with self._lock:
            write_record(
                self._fh,
                encode_event(time.time(), step, encode_histo_value(tag, np.asarray(values))),
            )

    def flush(self) -> None:
        with self._lock:
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def variable_summaries(writer: SummaryWriter, name: str, values, step: int) -> None:
    """Parity with the reference's ``variable_summaries`` (``demo1/train.py:15-24``):

    emits mean / stddev / max / min scalars plus a histogram for a tensor.
    Runs host-side on materialized arrays (summaries are not part of the jitted
    step — on TPU we keep the hot loop free of host syncs and sample summaries
    at eval boundaries instead).
    """
    arr = np.asarray(values)
    writer.add_scalars(
        {
            f"{name}/mean": float(arr.mean()),
            f"{name}/stddev": float(arr.std()),
            f"{name}/max": float(arr.max()),
            f"{name}/min": float(arr.min()),
        },
        step,
    )
    writer.add_histogram(f"{name}/histogram", arr, step)
