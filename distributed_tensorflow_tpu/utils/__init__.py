from distributed_tensorflow_tpu.utils.summary import SummaryWriter, variable_summaries  # noqa: F401
from distributed_tensorflow_tpu.utils.timer import StepTimer, WallClock  # noqa: F401
from distributed_tensorflow_tpu.utils.logging import get_logger  # noqa: F401
