"""Persistent XLA compilation cache for the CLIs.

Measured on this runtime: compiling Inception-v3 through the TPU tunnel
costs ~4-5 minutes, re-paid on EVERY retrain invocation — JAX's persistent
compilation cache is opt-in and nothing enabled it. Every CLI calls
:func:`enable_compilation_cache` right after parsing flags, so repeat runs
(the reference's own workflow: train, then the test CLI, then retrain again)
reuse compiled programs across processes.

Env overrides:
  DTF_COMPILATION_CACHE=<dir>   cache location
  DTF_COMPILATION_CACHE=0       disable
  DTF_SCOPED_VMEM_KIB=<n|0>     scoped-VMEM compiler budget (0 = leave the
                                XLA default alone)
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "distributed_tensorflow_tpu", "xla"
)

# XLA:TPU's default scoped-VMEM budget is 16 MiB of a v5e core's 128 MiB —
# measured (r5, tools/adam_fusion_probe.py era A/B): raising it to 32 MiB
# lets the compiler emit larger fusions/deeper prefetch around the flash
# custom calls and took the flagship LM step from 74.1% → 77.6% MFU
# (441 → 421 ms/step); 48/64 MiB plateau at the same value. Set via
# LIBTPU_INIT_ARGS, which libtpu snapshots at plugin init — so this must
# run before the first backend touch (every CLI calls
# enable_compilation_cache right after flag parsing, ahead of jax use).
_SCOPED_VMEM_FLAG = "--xla_tpu_scoped_vmem_limit_kib"
_SCOPED_VMEM_DEFAULT_KIB = 32768


def _configure_tpu_vmem_budget() -> None:
    kib = os.environ.get("DTF_SCOPED_VMEM_KIB", str(_SCOPED_VMEM_DEFAULT_KIB))
    if kib in ("0", ""):
        return
    try:
        kib_int = int(kib)
    except ValueError:
        # A malformed override must not turn startup into a crash (same
        # stance as the unwritable-cache-dir case below).
        import warnings

        warnings.warn(
            f"DTF_SCOPED_VMEM_KIB={kib!r} is not an integer; using "
            f"{_SCOPED_VMEM_DEFAULT_KIB}",
            stacklevel=3,
        )
        kib_int = _SCOPED_VMEM_DEFAULT_KIB
    existing = os.environ.get("LIBTPU_INIT_ARGS", "")
    if _SCOPED_VMEM_FLAG in existing:
        return  # operator already chose a value — respect it
    # libtpu snapshots its init args at plugin init: writing the env var
    # AFTER the backend is up would not change the budget in force, but
    # ops/attention._scoped_vmem_budget_kib reads this env var — a late
    # write would make the scratch gate size 4 MB fusions for a budget
    # the compiler doesn't actually have (a Mosaic scratch overflow at
    # the 16k D=32 remat shape, per the r5 A/B record). Leave the env
    # alone so the gate sizes for the real (default) budget. The check
    # rides a jax-private symbol (no public "is the backend up yet"
    # exists); if a future jax moves it, treat the state as unknown and
    # SKIP the write — startup must not crash, and the conservative gate
    # is the safe one.
    try:
        from jax._src.xla_bridge import backends_are_initialized
    except ImportError:
        import warnings

        warnings.warn(
            "jax._src.xla_bridge.backends_are_initialized is gone in this "
            "jax version; skipping the scoped-VMEM budget raise "
            f"({_SCOPED_VMEM_FLAG} stays at the XLA default — expect a few "
            "MFU points on TPU). Set LIBTPU_INIT_ARGS yourself to restore "
            "it, and update _configure_tpu_vmem_budget for this jax.",
            stacklevel=3,
        )
        return
    if backends_are_initialized():
        return
    os.environ["LIBTPU_INIT_ARGS"] = (
        f"{existing} {_SCOPED_VMEM_FLAG}={kib_int}".strip()
    )


def _cpu_cache_unsafe() -> bool:
    """jax/jaxlib < 0.5 mis-executes DESERIALIZED XLA:CPU executables:
    observed on 0.4.37 — a cache-hit resumed run computes NaN gradients on
    every step after the first and eventually segfaults, while the identical
    freshly-compiled program is bitwise correct (cache off → clean run).
    The persistent cache is purely an optimization, so on those versions it
    stays off for CPU-only runs; TPU/GPU keep the warm-cache speedups."""
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except (ValueError, AttributeError):
        return False
    if (major, minor) >= (0, 5):
        return False
    platforms = str(getattr(jax.config, "jax_platforms", None) or "") or os.environ.get(
        "JAX_PLATFORMS", ""
    )
    return platforms.strip().lower() == "cpu"


def enable_compilation_cache(directory: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``directory`` (default
    ``~/.cache/distributed_tensorflow_tpu/xla``; env override above).
    Returns the directory, or None when disabled. Safe to call repeatedly;
    the CACHE keys take effect before or after backend init (they only
    gate compile time). The TPU scoped-VMEM budget it also applies (module
    docstring) rides LIBTPU_INIT_ARGS, which libtpu snapshots at plugin
    init — call this BEFORE the first jax backend touch (every CLI does,
    right after flag parsing). Called after backend init it leaves
    LIBTPU_INIT_ARGS untouched (the budget in force stays at the XLA
    default AND the attention gate keeps sizing for that default —
    ops/attention._fused_bwd_scratch_limit)."""
    _configure_tpu_vmem_budget()
    env = os.environ.get("DTF_COMPILATION_CACHE")
    if env == "0":
        return None
    if _cpu_cache_unsafe():
        return None
    directory = env or directory or _DEFAULT
    import jax

    try:
        os.makedirs(directory, exist_ok=True)
    except OSError:
        # Purely an optimization — an unwritable HOME (CI containers) must
        # not turn it into a startup crash.
        return None
    jax.config.update("jax_compilation_cache_dir", directory)
    return directory
