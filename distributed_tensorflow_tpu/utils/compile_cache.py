"""Persistent XLA compilation cache for the CLIs.

Measured on this runtime: compiling Inception-v3 through the TPU tunnel
costs ~4-5 minutes, re-paid on EVERY retrain invocation — JAX's persistent
compilation cache is opt-in and nothing enabled it. Every CLI calls
:func:`enable_compilation_cache` right after parsing flags, so repeat runs
(the reference's own workflow: train, then the test CLI, then retrain again)
reuse compiled programs across processes.

Env overrides:
  DTF_COMPILATION_CACHE=<dir>   cache location
  DTF_COMPILATION_CACHE=0       disable
"""

from __future__ import annotations

import os

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "distributed_tensorflow_tpu", "xla"
)


def enable_compilation_cache(directory: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``directory`` (default
    ``~/.cache/distributed_tensorflow_tpu/xla``; env override above).
    Returns the directory, or None when disabled. Safe to call repeatedly
    and before/after backend init (config keys only gate compile time)."""
    env = os.environ.get("DTF_COMPILATION_CACHE")
    if env == "0":
        return None
    directory = env or directory or _DEFAULT
    import jax

    try:
        os.makedirs(directory, exist_ok=True)
    except OSError:
        # Purely an optimization — an unwritable HOME (CI containers) must
        # not turn it into a startup crash.
        return None
    jax.config.update("jax_compilation_cache_dir", directory)
    return directory
