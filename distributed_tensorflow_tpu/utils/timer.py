"""Wall-clock and step timing.

Parity with the reference's ``time.time()`` deltas printed as
``Training time:`` / ``Total time:`` (``demo1/train.py:152,164``;
``retrain1/retrain.py:373,423,468,476``), plus steps/sec tracking for the
bench harness.
"""

from __future__ import annotations

import time


class WallClock:
    """Elapsed wall-clock timer: ``WallClock()`` starts; ``.elapsed`` reads."""

    def __init__(self):
        self.start = time.time()

    @property
    def elapsed(self) -> float:
        return time.time() - self.start

    def lap(self) -> float:
        now = time.time()
        out = now - self.start
        self.start = now
        return out


class StepTimer:
    """Tracks steps/sec over a sliding window, excluding warmup/compile steps."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._count = 0
        self._timed_steps = 0
        self._timed_seconds = 0.0
        self._last = None

    def tick(self, steps: int = 1) -> None:
        """Record one dispatch covering ``steps`` optimizer steps."""
        now = time.time()
        if self._last is not None and self._count >= self.warmup_steps:
            self._timed_steps += steps
            self._timed_seconds += now - self._last
        self._last = now
        self._count += 1

    def mark(self, step: int | None = None) -> None:
        """Restart the current window at 'now' WITHOUT counting anything —
        call after boundary work (eval, summaries, checkpoint) so its time
        is excluded from the next training window's steps/sec. Pass the
        current ``step`` when using the tick_to API: a MID-window mark
        (e.g. a timed autosave) must also drop the partial window's steps,
        or the next tick_to would attribute them to post-mark time only."""
        self._last = time.time()
        if step is not None:
            self._last_step = step

    # -- drained-window convenience API (the loop.py / CLI idiom) ----------
    # Through the axon tunnel, per-dispatch ticks measure issue time, not
    # compute (bench.py docstring): tick ONLY at completion barriers.
    # ``start(step)`` marks t0 (and consumes one warmup slot, so with the
    # default warmup_steps=2 the first measured window — which contains the
    # jit compile — is dropped); ``tick_to(step)`` closes the window at a
    # barrier, attributing the steps since the last start/tick_to.

    def start(self, step: int) -> None:
        self.tick(0)
        self._last_step = step

    def tick_to(self, step: int) -> None:
        self.tick(step - self._last_step)
        self._last_step = step

    @property
    def steps_per_sec(self) -> float:
        if self._timed_seconds <= 0:
            return 0.0
        return self._timed_steps / self._timed_seconds
