"""Model-FLOPs accounting and chip peak throughput — the MFU denominator.

MFU (model FLOPs utilization) = model FLOPs executed per second / chip peak
FLOP/s. "Model FLOPs" counts only the mathematically required matmul work of
the model itself (fwd + bwd), NOT rematerialization recompute, and counts
causal attention at its actual half-triangle cost — the standard accounting
of the PaLM appendix / How-to-Scale-Your-Model, under which a perfectly
fused dense causal transformer tops out below 1.0 by definition.

The reference never measured compute efficiency at all (its README has no
numbers, ``/root/reference/README.md:1-2``); this module is what makes the
framework's per-chip performance story falsifiable and trackable per round.
"""

from __future__ import annotations

# bf16 peak matmul FLOP/s per chip, by jax device_kind substring (checked in
# order). Public spec-sheet numbers: TPU v4 275 T, v5e 197 T, v5p 459 T,
# v6e (Trillium) 918 T.
_PEAK_BF16 = (
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
)


def chip_peak_flops(device=None) -> float | None:
    """Peak bf16 FLOP/s of ``device`` (default: jax.devices()[0]), or None
    when unknown (e.g. the CPU backend) — callers should then report MFU as
    null rather than invent a denominator."""
    import jax

    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    if getattr(device, "platform", "") != "tpu":
        return None
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def transformer_train_flops(
    cfg, batch_size: int, seq_len: int | None = None, causal: bool = True
) -> int:
    """Model matmul FLOPs for ONE optimizer step (fwd + bwd) of
    ``TransformerLM(cfg)`` on ``(batch_size, seq_len)`` tokens.

    Accounting (2 FLOPs per MAC, backward = 2x forward, so train = 3x fwd):
      * parameter matmuls: per layer 4·d² (q,k,v,o) + 2·d·d_ff (ffn in/out),
        plus the d·vocab logits projection; fwd cost 2·T·N_matmul.
        Embedding lookup is a gather — 0 matmul FLOPs.
      * attention scores+values: per layer fwd 4·B·S²·d dense, halved for
        causal (the blockwise/flash kernels actually skip the masked half,
        and masked work isn't "model FLOPs" either way). With a sliding
        ``cfg.attention_window`` the causal count is the BANDED area —
        position i attends min(i+1, window) keys — so a windowed run's MFU
        is not credited the full triangle it never computes.
    Remat recompute is deliberately NOT counted — MFU measures useful work.
    """
    s = int(cfg.max_seq_len if seq_len is None else seq_len)
    b = int(batch_size)
    d = int(cfg.d_model)
    tokens = b * s
    # GQA (num_kv_heads < num_heads) shrinks the k/v projections: q and o
    # stay d x d, k/v are d x (kv_heads * head_dim) each.
    kv = int(cfg.kv_heads)
    kv_width = (d // cfg.num_heads) * kv
    n_matmul = (
        cfg.num_layers * (2 * d * d + 2 * d * kv_width + 2 * d * cfg.d_ff)
        + d * cfg.vocab_size
    )
    dense = 2 * tokens * n_matmul
    window = getattr(cfg, "attention_window", None)
    if causal and window is not None and window < s:
        # Exact attended (q, k) pair count of the band: the first `window`
        # rows ramp 1..window, the rest attend `window` keys each.
        pairs = window * (window + 1) // 2 + (s - window) * window
        attn = 4 * b * pairs * d * cfg.num_layers
    else:
        attn = 4 * b * s * s * d * cfg.num_layers
        if causal:
            attn //= 2
    return 3 * (dense + attn)


# HBM bandwidth (bytes/s) per chip, by device_kind substring — the decode
# roofline denominator (each KV-cache decode step re-reads the whole param
# tree, so tokens/s ≤ B · bw / param_bytes). Public spec-sheet numbers:
# v4 1228 GB/s, v5e 819 GB/s, v5p 2765 GB/s, v6e 1640 GB/s.
_HBM_BW = (
    ("v6e", 1640e9),
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9),
    ("v5litepod", 819e9),
    ("v5e", 819e9),
    ("v4", 1228e9),
)


def chip_hbm_bandwidth(device=None) -> float | None:
    """Peak HBM bytes/s of ``device`` (default: jax.devices()[0]), or None
    when unknown — callers report roofline fractions as absent, never
    invent a denominator."""
    import jax

    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    if getattr(device, "platform", "") != "tpu":
        return None
    for sub, bw in _HBM_BW:
        if sub in kind:
            return bw
    return None
