"""Env-var-driven fault-injection registry (the test harness's chaos monkey).

``DTT_FAULT=download:2,ckpt_save:1,nonfinite_grad:step=7`` arms sites by name:

* ``site:N`` — the next N traversals of ``site`` fire (count-armed);
* ``site:step=K`` — ``site`` fires exactly when the training loop passes
  host step K (step-armed; repeat the entry to arm several steps);
* ``site:p=F`` — each traversal fires with probability F (never exhausts;
  draws come from a ``DTT_FAULT_SEED``-seeded RNG so storms replay);
* ``site:after=N`` — let N traversals pass, fire once on the N+1th (repeat
  the entry to arm several crossings);
* ``site:ms=D`` — attach a latency of D milliseconds to the site: a
  delay-type site (``probe_slow``) stalls by D on every armed traversal,
  and an error-type site (``replica_hang``) reads D as its hang duration;
* ``site`` alone — shorthand for ``site:1``.

Entries for the same site combine: ``replica_hang:1,replica_hang:ms=500``
arms one hang of 500 ms. A site with ONLY ``ms=`` delays every traversal
while armed; combined with a count/probability/after arm, the delay applies
only when that arm fires.

Sites wired through the stack (each consumed exactly where the real failure
would occur, so recovery paths are exercised end-to-end):

* ``download``       — network fetch body in ``data/download.py`` (inside the
                       retry loop, so backoff recovers it);
* ``ckpt_save``      — Orbax write in ``train/checkpoint.py`` (inside retry);
* ``ckpt_restore``   — Orbax read in ``train/checkpoint.py`` (inside retry,
                       then the walk-back loop);
* ``nonfinite_grad`` — step-armed: the training loop poisons that step's
                       batch with NaN, driving the non-finite guard;
* ``preempt``        — step-armed: the loop raises a synthetic preemption
                       request at that step (same flag a real SIGTERM sets);
* ``ckpt_publish``   — manifest publish in ``train/checkpoint.py`` (the
                       rename that makes a checkpoint visible to watchers).

Serving-plane sites (PR 16, DESIGN.md §22 for the outcome each maps to):

* ``route_dispatch``        — router→replica connect fails before any bytes;
* ``replica_5xx``           — replica answers 503 before admission;
* ``replica_stall``         — replica stalls ``ms=`` before answering;
* ``replica_hang``          — replica holds the socket open without answering
                              (``ms=`` caps the hold, default 30 000);
* ``stream_cut``            — SSE stream closes without a ``done`` frame
                              (``after=N`` lets N token frames pass);
* ``probe_slow``            — health probe stalls ``ms=``;
* ``probe_flap``            — health probe reports failure for a live replica;
* ``handoff_corrupt``       — outbound DTFH1 bundle is bit-flipped;
* ``handoff_send_timeout``  — outbound handoff send dies on a timeout;
* ``spawn_fail``            — supervisor replica spawn raises;
* ``deploy_nan``            — deploy watcher's canary forward pass sees a
                              non-finite logit (drives the rollback gate);
* ``rollout_push``          — rollout controller's admin-deploy delivery
                              fails mid-walk (typed halt + fleet rollback);
* ``rollout_slo_flap``      — canary ramp sees a synthetic SLO breach
                              (narrow-to-first-rung, never widen on noise).

The registry is process-local and loads from the env on first use, so
multiprocess tests arm workers simply by exporting ``DTT_FAULT``.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Iterable

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_VAR = "DTT_FAULT"
SEED_ENV_VAR = "DTT_FAULT_SEED"


class InjectedFault(OSError):
    """Deliberately an OSError subclass: injected faults flow through the
    same retry/except paths real transient I/O errors do."""


@dataclass
class _Site:
    remaining: int = 0
    steps: set[int] = field(default_factory=set)
    p: float = 0.0            # per-traversal fire probability (never exhausts)
    afters: set[int] = field(default_factory=set)  # fire once past each crossing
    ms: float = 0.0           # attached latency (delay value / hang duration)
    seen: int = 0             # traversals observed (drives ``after=``)
    gated: bool = False       # ever count/p/after-armed: ms only fires with arm


_lock = threading.Lock()
_registry: dict[str, _Site] | None = None  # None = not yet loaded from env
_rng: random.Random = random.Random()


def parse_spec(spec: str) -> dict[str, _Site]:
    """Parse the ``DTT_FAULT`` grammar; raises ValueError on malformed input
    (a silently-ignored typo in a fault spec would fake a passing test)."""
    sites: dict[str, _Site] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        name, _, arg = entry.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"bad {ENV_VAR} entry {entry!r}: empty site name")
        site = sites.setdefault(name, _Site())
        arg = arg.strip()
        if not arg:
            site.remaining += 1
        elif arg.isdigit():
            site.remaining += int(arg)
        elif arg.startswith("step="):
            site.steps.add(int(arg[len("step="):]))
        elif arg.startswith("p="):
            p = float(arg[len("p="):])
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"bad {ENV_VAR} entry {entry!r}: p must be in [0, 1]")
            site.p = p
        elif arg.startswith("after="):
            site.afters.add(int(arg[len("after="):]))
        elif arg.startswith("ms="):
            ms = float(arg[len("ms="):])
            if ms < 0:
                raise ValueError(
                    f"bad {ENV_VAR} entry {entry!r}: ms must be >= 0")
            site.ms = ms
        else:
            raise ValueError(
                f"bad {ENV_VAR} entry {entry!r}: expected 'site', 'site:N', "
                "'site:step=K', 'site:p=F', 'site:after=N' or 'site:ms=D'"
            )
        if site.remaining > 0 or site.p > 0 or site.afters:
            site.gated = True
    return sites


def configure(spec: str | None) -> None:
    """Install a spec programmatically (tests); ``None`` re-arms from the env
    on next use."""
    global _registry
    with _lock:
        _registry = None if spec is None else parse_spec(spec)
        _rng.seed(int(os.environ.get(SEED_ENV_VAR, "0")))


def reset() -> None:
    configure(None)


def _sites() -> dict[str, _Site]:
    global _registry
    if _registry is None:
        _registry = parse_spec(os.environ.get(ENV_VAR, ""))
        _rng.seed(int(os.environ.get(SEED_ENV_VAR, "0")))
        if _registry:
            log.warning("%s armed: %s", ENV_VAR, os.environ.get(ENV_VAR))
    return _registry


def _roll(s: _Site) -> bool:
    """One traversal of a site, lock held: count, crossing, then p-arm."""
    s.seen += 1
    if s.remaining > 0:
        s.remaining -= 1
        return True
    crossed = {a for a in s.afters if s.seen > a}
    if crossed:
        s.afters -= crossed
        return True
    return s.p > 0.0 and _rng.random() < s.p


def fire(site: str) -> bool:
    """One traversal of ``site``; True when a count-, after-, or p-armed
    shot fires (counts consume, crossings fire once, p never exhausts)."""
    with _lock:
        s = _sites().get(site)
        if s is None or not _roll(s):
            return False
    log.warning("injected fault fired: %s", site)
    return True


def fire_step(site: str, steps: Iterable[int]) -> bool:
    """Consume any step-armed shots of ``site`` within ``steps`` (a fused
    dispatch spans a step range); True when at least one fires."""
    with _lock:
        s = _sites().get(site)
        if s is None or not s.steps:
            return False
        hit = s.steps.intersection(steps)
        if not hit:
            return False
        s.steps -= hit
    log.warning("injected fault fired: %s at step(s) %s", site, sorted(hit))
    return True


def maybe_fail(site: str, detail: str = "") -> None:
    """Raise :class:`InjectedFault` when ``site`` fires on this traversal."""
    if fire(site):
        raise InjectedFault(f"injected fault at {site}" + (f" ({detail})" if detail else ""))


def site_ms(site: str, default: float = 0.0) -> float:
    """The ``ms=`` latency attached to ``site`` (non-consuming) — error-type
    sites read it as a duration (e.g. how long ``replica_hang`` holds the
    socket)."""
    with _lock:
        s = _sites().get(site)
        return s.ms if s is not None and s.ms > 0 else default


def delay_s(site: str) -> float:
    """Seconds to stall this traversal of ``site``, 0.0 when quiet.

    A site armed ONLY with ``ms=`` delays every traversal; combined with a
    count/probability/after arm, the delay applies when that arm fires."""
    with _lock:
        s = _sites().get(site)
        if s is None or s.ms <= 0:
            return 0.0
        if s.gated:  # an exhausted count/after arm stays quiet, not ms-only
            if not _roll(s):
                return 0.0
        else:
            s.seen += 1
        out = s.ms / 1000.0
    log.warning("injected delay fired: %s (%.0f ms)", site, out * 1000.0)
    return out
