"""Env-var-driven fault-injection registry (the test harness's chaos monkey).

``DTT_FAULT=download:2,ckpt_save:1,nonfinite_grad:step=7`` arms sites by name:

* ``site:N`` — the next N traversals of ``site`` fire (count-armed);
* ``site:step=K`` — ``site`` fires exactly when the training loop passes
  host step K (step-armed; repeat the entry to arm several steps);
* ``site`` alone — shorthand for ``site:1``.

Sites wired through the stack (each consumed exactly where the real failure
would occur, so recovery paths are exercised end-to-end):

* ``download``       — network fetch body in ``data/download.py`` (inside the
                       retry loop, so backoff recovers it);
* ``ckpt_save``      — Orbax write in ``train/checkpoint.py`` (inside retry);
* ``ckpt_restore``   — Orbax read in ``train/checkpoint.py`` (inside retry,
                       then the walk-back loop);
* ``nonfinite_grad`` — step-armed: the training loop poisons that step's
                       batch with NaN, driving the non-finite guard;
* ``preempt``        — step-armed: the loop raises a synthetic preemption
                       request at that step (same flag a real SIGTERM sets).

The registry is process-local and loads from the env on first use, so
multiprocess tests arm workers simply by exporting ``DTT_FAULT``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Iterable

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_VAR = "DTT_FAULT"


class InjectedFault(OSError):
    """Deliberately an OSError subclass: injected faults flow through the
    same retry/except paths real transient I/O errors do."""


@dataclass
class _Site:
    remaining: int = 0
    steps: set[int] = field(default_factory=set)


_lock = threading.Lock()
_registry: dict[str, _Site] | None = None  # None = not yet loaded from env


def parse_spec(spec: str) -> dict[str, _Site]:
    """Parse the ``DTT_FAULT`` grammar; raises ValueError on malformed input
    (a silently-ignored typo in a fault spec would fake a passing test)."""
    sites: dict[str, _Site] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        name, _, arg = entry.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"bad {ENV_VAR} entry {entry!r}: empty site name")
        site = sites.setdefault(name, _Site())
        arg = arg.strip()
        if not arg:
            site.remaining += 1
        elif arg.isdigit():
            site.remaining += int(arg)
        elif arg.startswith("step="):
            site.steps.add(int(arg[len("step="):]))
        else:
            raise ValueError(
                f"bad {ENV_VAR} entry {entry!r}: expected 'site', 'site:N' "
                "or 'site:step=K'"
            )
    return sites


def configure(spec: str | None) -> None:
    """Install a spec programmatically (tests); ``None`` re-arms from the env
    on next use."""
    global _registry
    with _lock:
        _registry = None if spec is None else parse_spec(spec)


def reset() -> None:
    configure(None)


def _sites() -> dict[str, _Site]:
    global _registry
    if _registry is None:
        _registry = parse_spec(os.environ.get(ENV_VAR, ""))
        if _registry:
            log.warning("%s armed: %s", ENV_VAR, os.environ.get(ENV_VAR))
    return _registry


def fire(site: str) -> bool:
    """Consume one count-armed shot of ``site``; True when it fires."""
    with _lock:
        s = _sites().get(site)
        if s is None or s.remaining <= 0:
            return False
        s.remaining -= 1
    log.warning("injected fault fired: %s", site)
    return True


def fire_step(site: str, steps: Iterable[int]) -> bool:
    """Consume any step-armed shots of ``site`` within ``steps`` (a fused
    dispatch spans a step range); True when at least one fires."""
    with _lock:
        s = _sites().get(site)
        if s is None or not s.steps:
            return False
        hit = s.steps.intersection(steps)
        if not hit:
            return False
        s.steps -= hit
    log.warning("injected fault fired: %s at step(s) %s", site, sorted(hit))
    return True


def maybe_fail(site: str, detail: str = "") -> None:
    """Raise :class:`InjectedFault` when ``site`` is count-armed."""
    if fire(site):
        raise InjectedFault(f"injected fault at {site}" + (f" ({detail})" if detail else ""))
