"""Profiling / tracing subsystem.

The reference's only "profiling" is wall-clock ``time.time()`` deltas printed
to stdout (``demo1/train.py:152,164``) plus graph visualisation via
``FileWriter(..., sess.graph)`` (``demo1/train.py:151``) — SURVEY §5.1. The
TPU-native upgrade is a real XLA trace: ``jax.profiler`` writes a
TensorBoard-loadable profile (XPlane) with per-op device timelines, HLO, and
memory-allocation views.

Three entry points:

* :class:`Profiler` — step-windowed tracing for training loops: arm it with a
  ``[start_step, start_step + num_steps)`` window and call ``.step(i)`` once
  per loop iteration; the trace starts/stops itself and each step inside the
  window is annotated with ``StepTraceAnnotation`` so TensorBoard groups
  device ops by step.
* :func:`trace` — context manager for ad-hoc tracing of any region.
* :func:`annotate` — named ``TraceAnnotation`` for host-side regions so they
  show up on the trace timeline.

All are no-ops when given an empty/None log dir, so call sites need no
conditionals.
"""

from __future__ import annotations

import contextlib
import os

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Profiler:
    """Step-windowed ``jax.profiler`` trace for a training loop.

    Usage::

        prof = Profiler(log_dir, start_step=10, num_steps=5)
        for step in range(n):
            with prof.step(step):
                run_one_step()
        prof.close()  # safety net if the loop exits inside the window

    ``start_step`` defaults past the compile steps so the trace captures
    steady-state device time, not XLA compilation.

    ``sync`` (if given) is called right before the trace is stopped. Training
    loops dispatch steps asynchronously, so without a device sync the host
    reaches the end of the window while the device is still executing traced
    steps and the XPlane is truncated; pass e.g.
    ``lambda: jax.block_until_ready(self.global_step)`` — device execution is
    in-order, so blocking on the window's last output flushes all of it.
    """

    def __init__(
        self,
        log_dir: str | None,
        start_step: int = 10,
        num_steps: int = 5,
        sync=None,
    ):
        self.log_dir = log_dir or None
        self.start_step = start_step
        self.num_steps = num_steps
        self.sync = sync
        self._active = False
        self._done = False
        self._seen_spans: set[int] = set()
        self._deferred = False
        self._traced = 0
        self._first_step: int | None = None

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    def step(self, step: int, span: int = 1):
        """Context manager wrapping one training dispatch covering optimizer
        steps ``[step, step + span)`` (span > 1 = fused multi-step chunks);
        manages the trace window. The window triggers when it INTERSECTS the
        dispatch's range — with fused chunks a strict membership test could
        skip past the window entirely and never record a trace.

        One exception: if the window would open on a dispatch whose fused
        chunk length (``span``) has never been dispatched before, while
        ``start_step`` asks to skip past the run's beginning, the open is
        deferred to the next dispatch with an already-seen span. A
        never-seen span means a fresh jit compile (the cache is keyed on
        the chunk length): ``start_step`` exists precisely to skip
        compilation, and with fused chunks the bare intersection test
        would otherwise start the trace around the compile and swamp the
        XPlane with host time. Set ``start_step=0`` (or <= the resume
        step) to opt into tracing the first dispatch anyway. Once open,
        the trace covers at least ``num_steps`` optimizer steps' worth of
        dispatches."""
        if not self.enabled or self._done:
            return contextlib.nullcontext()
        if self._first_step is None:
            self._first_step = step
        if self._active and self._traced >= self.num_steps:
            self._stop()
            self._seen_spans.add(span)
            return contextlib.nullcontext()
        window_end = self.start_step + self.num_steps
        if not self._active:
            intersects = step < window_end and step + span > self.start_step
            # Opt-in: a start_step at/before the run's first step means the
            # caller wants the first (compiling) dispatch traced. Otherwise
            # never open around a chunk length's first-ever dispatch — that
            # is where its jit compile happens (including tail chunks whose
            # first appearance is mid-run, not just the run's first call).
            opt_in = self.start_step <= self._first_step
            if intersects or self._deferred:
                if not opt_in and span not in self._seen_spans:
                    self._deferred = True
                else:
                    self._start()
        self._seen_spans.add(span)
        if self._active:
            self._traced += span
            import jax

            return jax.profiler.StepTraceAnnotation("train", step_num=step)
        return contextlib.nullcontext()

    def _start(self) -> None:
        import jax

        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        log.info("profiler: trace started -> %s", self.log_dir)

    def _stop(self) -> None:
        import jax

        if self.sync is not None:
            self.sync()
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        log.info("profiler: trace written to %s", self.log_dir)

    def close(self) -> None:
        """Stop the trace if the loop ended while it was still active; warn if
        the run finished before the window ever opened (else an empty profile
        dir would be the only clue)."""
        if self._active:
            self._stop()
        elif self.enabled and not self._done:
            hint = (
                " (window deferred past the run's only dispatch — the first "
                "dispatch compiles; set start_step=0 to trace it anyway, or "
                "lower steps_per_call)"
                if self._deferred
                else ""
            )
            log.warning(
                "profiler: run ended before the trace window opened "
                "(start_step=%d, num_steps=%d) — no profile written to %s%s",
                self.start_step, self.num_steps, self.log_dir, hint,
            )


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Trace an arbitrary region: ``with trace('./prof'): run()``. No-op when
    ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler: trace written to %s", log_dir)


def annotate(name: str, **kwargs):
    """Named host-side region annotation visible on the trace timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


def save_device_memory_profile(path: str) -> None:
    """Dump a pprof-format snapshot of live device (HBM) allocations."""
    import jax

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    log.info("profiler: device memory profile -> %s", path)
