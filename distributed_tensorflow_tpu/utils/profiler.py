"""Profiling / tracing subsystem.

The reference's only "profiling" is wall-clock ``time.time()`` deltas printed
to stdout (``demo1/train.py:152,164``) plus graph visualisation via
``FileWriter(..., sess.graph)`` (``demo1/train.py:151``) — SURVEY §5.1. The
TPU-native upgrade is a real XLA trace: ``jax.profiler`` writes a
TensorBoard-loadable profile (XPlane) with per-op device timelines, HLO, and
memory-allocation views.

Three entry points:

* :class:`Profiler` — step-windowed tracing for training loops: arm it with a
  ``[start_step, start_step + num_steps)`` window and call ``.step(i)`` once
  per loop iteration; the trace starts/stops itself and each step inside the
  window is annotated with ``StepTraceAnnotation`` so TensorBoard groups
  device ops by step.
* :func:`trace` — context manager for ad-hoc tracing of any region.
* :func:`annotate` — named ``TraceAnnotation`` for host-side regions so they
  show up on the trace timeline.

All are no-ops when given an empty/None log dir, so call sites need no
conditionals.
"""

from __future__ import annotations

import contextlib
import os

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)


class Profiler:
    """Step-windowed ``jax.profiler`` trace for a training loop.

    Usage::

        prof = Profiler(log_dir, start_step=10, num_steps=5)
        for step in range(n):
            with prof.step(step):
                run_one_step()
        prof.close()  # safety net if the loop exits inside the window

    ``start_step`` defaults past the compile steps so the trace captures
    steady-state device time, not XLA compilation.

    ``sync`` (if given) is called right before the trace is stopped. Training
    loops dispatch steps asynchronously, so without a device sync the host
    reaches the end of the window while the device is still executing traced
    steps and the XPlane is truncated; pass e.g.
    ``lambda: jax.block_until_ready(self.global_step)`` — device execution is
    in-order, so blocking on the window's last output flushes all of it.
    """

    def __init__(
        self,
        log_dir: str | None,
        start_step: int = 10,
        num_steps: int = 5,
        sync=None,
    ):
        self.log_dir = log_dir or None
        self.start_step = start_step
        self.num_steps = num_steps
        self.sync = sync
        self._active = False
        self._done = False

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    def step(self, step: int, span: int = 1):
        """Context manager wrapping one training dispatch covering optimizer
        steps ``[step, step + span)`` (span > 1 = fused multi-step chunks);
        manages the trace window. The window triggers when it INTERSECTS the
        dispatch's range — with fused chunks a strict membership test could
        skip past the window entirely and never record a trace."""
        if not self.enabled or self._done:
            return contextlib.nullcontext()
        window_end = self.start_step + self.num_steps
        if not self._active and step < window_end and step + span > self.start_step:
            self._start()
        if self._active and step >= window_end:
            self._stop()
            return contextlib.nullcontext()
        if self._active:
            import jax

            return jax.profiler.StepTraceAnnotation("train", step_num=step)
        return contextlib.nullcontext()

    def _start(self) -> None:
        import jax

        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        log.info("profiler: trace started -> %s", self.log_dir)

    def _stop(self) -> None:
        import jax

        if self.sync is not None:
            self.sync()
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        log.info("profiler: trace written to %s", self.log_dir)

    def close(self) -> None:
        """Stop the trace if the loop ended while it was still active; warn if
        the run finished before the window ever opened (else an empty profile
        dir would be the only clue)."""
        if self._active:
            self._stop()
        elif self.enabled and not self._done:
            log.warning(
                "profiler: run ended before the trace window opened "
                "(start_step=%d, num_steps=%d) — no profile written to %s",
                self.start_step, self.num_steps, self.log_dir,
            )


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Trace an arbitrary region: ``with trace('./prof'): run()``. No-op when
    ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler: trace written to %s", log_dir)


def annotate(name: str, **kwargs):
    """Named host-side region annotation visible on the trace timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


def save_device_memory_profile(path: str) -> None:
    """Dump a pprof-format snapshot of live device (HBM) allocations."""
    import jax

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    log.info("profiler: device memory profile -> %s", path)
