"""Version compatibility shims for the JAX API surface this package uses.

The codebase targets the modern spelling ``jax.shard_map(..., check_vma=)``.
Older jaxlibs (< 0.5) only ship ``jax.experimental.shard_map.shard_map`` with
the ``check_rep=`` keyword; without a shim every train/eval step builder dies
with ``AttributeError: module 'jax' has no attribute 'shard_map'`` on such
environments. Installing the alias once at package import keeps every call
site on the one modern spelling instead of scattering try/except fallbacks
through ten modules.
"""

from __future__ import annotations


def install() -> None:
    """Idempotently provide the modern spellings this package calls."""
    import jax

    if not hasattr(jax, "shard_map"):
        import inspect

        from jax.experimental.shard_map import shard_map as _shard_map

        has_check_vma = "check_vma" in inspect.signature(_shard_map).parameters

        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
            if check_vma is not None:
                # Same meaning, renamed: check_rep (old) -> check_vma (new).
                kwargs["check_vma" if has_check_vma else "check_rep"] = check_vma
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
            )

        jax.shard_map = shard_map

    if not hasattr(jax.distributed, "is_initialized"):
        # Added to jax.distributed in 0.5; older versions expose the client
        # handle on the internal global state.
        def is_initialized() -> bool:
            from jax._src import distributed as _dist

            return getattr(_dist.global_state, "client", None) is not None

        jax.distributed.is_initialized = is_initialized

    if not hasattr(jax.lax, "axis_size"):
        # lax.axis_size(name) predates nothing semantically: the size of a
        # mapped axis is psum(1) over it.
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size
