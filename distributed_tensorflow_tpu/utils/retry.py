"""Retry with exponential backoff + jitter.

The resilience layer's answer to transient I/O failure (ROADMAP north-star:
flaky networks, preempted storage): the reference's ``maybe_download_and_extract``
died on the first ``URLError`` and every Orbax save/restore was one-shot.
Callers wrap just the failure-prone body (the socket read, the Orbax write) —
never verification logic, whose failures are deterministic.

Backoff: ``base_delay * 2**(attempt-1)`` capped at ``max_delay``, then scaled
by a uniform jitter factor in ``[1-jitter, 1+jitter]`` so a fleet of workers
retrying the same dead endpoint doesn't thundering-herd it in lockstep.
"""

from __future__ import annotations

import math
import random
import time
from functools import wraps
from typing import Callable, Iterable, TypeVar

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

T = TypeVar("T")

# OSError covers socket errors, timeouts, urllib.error.URLError, filesystem
# errors, and utils.faults.InjectedFault — the transient-failure family.
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (OSError,)


class Budget:
    """Remaining-time budget a request carries across hops (deadline
    propagation, DESIGN.md §22): constructed once at the edge, every hop
    asks ``remaining()`` instead of re-deriving its own deadline.

    ``seconds=None`` means unbounded (``remaining()`` is +inf, never
    ``expired()``) so budget-aware code paths need no None-checks."""

    def __init__(self, seconds: float | None, *,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.deadline = None if seconds is None else clock() + seconds

    def remaining(self) -> float:
        if self.deadline is None:
            return math.inf
        return self.deadline - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


def next_delay(
    attempt: int,
    *,
    base_delay: float,
    max_delay: float,
    jitter: float,
    rng: random.Random,
) -> float:
    """Backoff delay before 1-based retry ``attempt`` (the single-step form
    of :func:`backoff_delays`; the fleet router uses it per failover hop)."""
    delay = min(max_delay, base_delay * 2 ** (attempt - 1))
    return delay * (1.0 - jitter + 2.0 * jitter * rng.random())


def backoff_delays(
    attempts: int,
    base_delay: float,
    max_delay: float,
    jitter: float,
    rng: random.Random,
) -> list[float]:
    """The (attempts-1) sleep durations between attempts — exposed so tests
    can assert the timing envelope without sleeping."""
    return [
        next_delay(attempt, base_delay=base_delay, max_delay=max_delay,
                   jitter=jitter, rng=rng)
        for attempt in range(1, attempts)
    ]


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    jitter: float = 0.25,
    retryable: Iterable[type[BaseException]] = DEFAULT_RETRYABLE,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    description: str = "",
) -> T:
    """Call ``fn()`` up to ``attempts`` times; re-raise the last error.

    Only ``retryable`` exception types are retried — anything else (a sha256
    mismatch, a template shape error) propagates immediately: deterministic
    failures don't get better with patience.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    retryable = tuple(retryable)
    rng = rng if rng is not None else random.Random()
    delays = backoff_delays(attempts, base_delay, max_delay, jitter, rng)
    what = description or getattr(fn, "__name__", "call")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retryable as e:
            if attempt == attempts:
                log.warning("%s: attempt %d/%d failed (%s) — giving up",
                            what, attempt, attempts, e)
                raise
            delay = delays[attempt - 1]
            log.warning("%s: attempt %d/%d failed (%s) — retrying in %.2fs",
                        what, attempt, attempts, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")


def deadline_retry_call(
    fn: Callable[[], T],
    *,
    budget: Budget,
    attempts: int = 3,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    jitter: float = 0.25,
    min_attempt_s: float = 0.0,
    retryable: Iterable[type[BaseException]] = DEFAULT_RETRYABLE,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    description: str = "",
) -> T:
    """:func:`retry_call` that stops when the remaining ``budget`` can't fit
    the backoff sleep plus one more attempt (``min_attempt_s`` estimates the
    attempt's own cost). The last real error is re-raised — a request out of
    budget fails with what actually went wrong, not a synthetic timeout."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    retryable = tuple(retryable)
    rng = rng if rng is not None else random.Random()
    what = description or getattr(fn, "__name__", "call")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retryable as e:
            if attempt == attempts:
                log.warning("%s: attempt %d/%d failed (%s) — giving up",
                            what, attempt, attempts, e)
                raise
            delay = next_delay(attempt, base_delay=base_delay,
                               max_delay=max_delay, jitter=jitter, rng=rng)
            if budget.remaining() < delay + min_attempt_s:
                log.warning(
                    "%s: attempt %d/%d failed (%s) — %.2fs budget left, "
                    "can't fit %.2fs backoff + another attempt, giving up",
                    what, attempt, attempts, e, max(budget.remaining(), 0.0),
                    delay)
                raise
            log.warning("%s: attempt %d/%d failed (%s) — retrying in %.2fs",
                        what, attempt, attempts, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")


def retrying(**retry_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(
                lambda: fn(*args, **kwargs),
                description=fn.__qualname__,
                **retry_kwargs,
            )

        return wrapper

    return deco
