"""Retry with exponential backoff + jitter.

The resilience layer's answer to transient I/O failure (ROADMAP north-star:
flaky networks, preempted storage): the reference's ``maybe_download_and_extract``
died on the first ``URLError`` and every Orbax save/restore was one-shot.
Callers wrap just the failure-prone body (the socket read, the Orbax write) —
never verification logic, whose failures are deterministic.

Backoff: ``base_delay * 2**(attempt-1)`` capped at ``max_delay``, then scaled
by a uniform jitter factor in ``[1-jitter, 1+jitter]`` so a fleet of workers
retrying the same dead endpoint doesn't thundering-herd it in lockstep.
"""

from __future__ import annotations

import random
import time
from functools import wraps
from typing import Callable, Iterable, TypeVar

from distributed_tensorflow_tpu.utils.logging import get_logger

log = get_logger(__name__)

T = TypeVar("T")

# OSError covers socket errors, timeouts, urllib.error.URLError, filesystem
# errors, and utils.faults.InjectedFault — the transient-failure family.
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (OSError,)


def backoff_delays(
    attempts: int,
    base_delay: float,
    max_delay: float,
    jitter: float,
    rng: random.Random,
) -> list[float]:
    """The (attempts-1) sleep durations between attempts — exposed so tests
    can assert the timing envelope without sleeping."""
    out = []
    for attempt in range(1, attempts):
        delay = min(max_delay, base_delay * 2 ** (attempt - 1))
        out.append(delay * (1.0 - jitter + 2.0 * jitter * rng.random()))
    return out


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    jitter: float = 0.25,
    retryable: Iterable[type[BaseException]] = DEFAULT_RETRYABLE,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    description: str = "",
) -> T:
    """Call ``fn()`` up to ``attempts`` times; re-raise the last error.

    Only ``retryable`` exception types are retried — anything else (a sha256
    mismatch, a template shape error) propagates immediately: deterministic
    failures don't get better with patience.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    retryable = tuple(retryable)
    rng = rng if rng is not None else random.Random()
    delays = backoff_delays(attempts, base_delay, max_delay, jitter, rng)
    what = description or getattr(fn, "__name__", "call")
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retryable as e:
            if attempt == attempts:
                log.warning("%s: attempt %d/%d failed (%s) — giving up",
                            what, attempt, attempts, e)
                raise
            delay = delays[attempt - 1]
            log.warning("%s: attempt %d/%d failed (%s) — retrying in %.2fs",
                        what, attempt, attempts, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")


def retrying(**retry_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(
                lambda: fn(*args, **kwargs),
                description=fn.__qualname__,
                **retry_kwargs,
            )

        return wrapper

    return deco
