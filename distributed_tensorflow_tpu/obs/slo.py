"""Declarative SLO monitor: rules over registry metrics, evaluated on a
ticker, with sustained-breach semantics and machine-readable status.

A rule is ``(metric selector, aggregation, threshold, direction,
sustain window)``. Evaluation reads the CURRENT registry state — a gauge's
value, a counter's total, a histogram's reservoir percentile — compares it
against the threshold, and runs a tiny state machine per rule:

    ok ──condition holds──▶ pending ──held for sustain_s──▶ breach
    ▲                                                          │
    └────────────────condition clears──────────────────────────┘

(``sustain_s=0`` collapses pending: first bad reading breaches.) On the
ok→breach transition the monitor increments ``slo_breach_total{rule=...}``,
emits a ``trace_event`` AND a flight-recorder entry (a later crash dump
shows which SLOs were burning when it happened), and invokes every
registered callback — the hook the serving-fleet router will use for
autoscale/drain decisions. Recovery (breach→ok) fires callbacks too, with
``status="ok"``.

Missing metrics read as ``no_data`` and never breach: a rule about a
histogram that hasn't seen traffic yet must not page anybody.

Rules come from :func:`default_serving_rules` / :func:`default_training_rules`
or the ``--slo`` flag's compact spec syntax (:func:`parse_slo_spec`):

    metric[:aggregation][{label=value,...}] >|< threshold [@sustain_s] [#name]

    serve_ttft_seconds:p99>0.5@5      p99 TTFT above 500 ms for 5 s
    recompile_events_total>0          any post-warmup recompile (instant)
    train_data_wait_frac>0.5@30       input-bound for 30 s

``evaluate()`` is cheap for value rules and one reservoir sort for
percentile rules, which is why the production wiring runs it on a ticker
(~1 Hz) or at eval boundaries, never per step — ``bench_obs_overhead``
accounts its cost as evaluate_cost/interval of wall time.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field

from distributed_tensorflow_tpu.obs import recorder as _recorder
from distributed_tensorflow_tpu.obs import registry as _registry
from distributed_tensorflow_tpu.obs import trace as _trace

__all__ = [
    "SloRule",
    "SloMonitor",
    "parse_slo_spec",
    "parse_slo_flag",
    "default_serving_rules",
    "default_training_rules",
    "default_fleet_rules",
]

_AGGREGATIONS = ("value", "mean", "max", "count", "p50", "p95", "p99")


@dataclass
class SloRule:
    """One objective: ``aggregation(metric)`` vs ``threshold``.

    ``direction="above"`` breaches when the value exceeds the threshold
    (latency/queue/error rules); ``"below"`` when it drops under it
    (throughput floors). ``labels`` restricts a labeled family to children
    matching every given (name, value) pair; unlabeled rules aggregate
    over ALL children (sum for counters, max for gauges — the conservative
    fleet reading)."""

    name: str
    metric: str
    threshold: float
    aggregation: str = "value"
    direction: str = "above"
    sustain_s: float = 0.0
    labels: dict = field(default_factory=dict)
    description: str = ""

    def __post_init__(self):
        if self.aggregation not in _AGGREGATIONS:
            raise ValueError(
                f"rule {self.name}: unknown aggregation {self.aggregation!r} "
                f"(choose from {_AGGREGATIONS})")
        if self.direction not in ("above", "below"):
            raise ValueError(
                f"rule {self.name}: direction must be above|below, "
                f"got {self.direction!r}")
        if self.sustain_s < 0:
            raise ValueError(f"rule {self.name}: sustain_s must be >= 0")


_SPEC_RE = re.compile(
    r"^(?P<metric>[A-Za-z_][A-Za-z0-9_]*)"
    r"(?::(?P<agg>[a-z0-9]+))?"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s*(?P<dir>[<>])\s*"
    r"(?P<thr>[-+0-9.eE]+)"
    r"(?:@(?P<sustain>[0-9.]+))?"
    r"(?:#(?P<name>[A-Za-z0-9_.-]+))?$"
)


def parse_slo_spec(spec: str) -> SloRule:
    """One compact rule spec → :class:`SloRule` (syntax in the module
    docstring). Raises ValueError on malformed specs — a typo'd SLO that
    silently monitors nothing is worse than a crash at startup."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(f"malformed SLO spec {spec!r} "
                         "(want metric[:agg][{k=v}]>threshold[@sustain][#name])")
    labels = {}
    if m.group("labels"):
        for pair in m.group("labels").split(","):
            k, _, v = pair.partition("=")
            labels[k.strip()] = v.strip().strip('"')
    agg = m.group("agg") or "value"
    return SloRule(
        name=m.group("name") or f"{m.group('metric')}_{agg}",
        metric=m.group("metric"),
        aggregation=agg,
        threshold=float(m.group("thr")),
        direction="above" if m.group("dir") == ">" else "below",
        sustain_s=float(m.group("sustain") or 0.0),
        labels=labels,
    )


def parse_slo_flag(flag: str, *, defaults=None) -> list:
    """``--slo`` value → rules. Comma-separated specs; the bare token
    ``default`` expands to ``defaults`` (a zero-arg callable returning
    rules); ``off``/empty yields no rules."""
    rules: list = []
    for part in (flag or "").split(","):
        part = part.strip()
        if not part or part == "off":
            continue
        if part == "default":
            if defaults is not None:
                rules.extend(defaults())
            continue
        rules.append(parse_slo_spec(part))
    return rules


def default_serving_rules(
    *,
    ttft_p99_s: float = 0.5,
    queue_depth: float = 48,
    sustain_s: float = 5.0,
) -> list:
    """The serving SLOs every replica should watch: tail TTFT, queue
    buildup, and the zero-recompile invariant (threshold 0, instant —
    one post-warmup compile is already a bug)."""
    return [
        SloRule("ttft_p99", "serve_ttft_seconds", ttft_p99_s,
                aggregation="p99", sustain_s=sustain_s,
                description="p99 time-to-first-token"),
        SloRule("queue_depth", "serve_queue_depth_current", queue_depth,
                sustain_s=sustain_s,
                description="admission queue backlog"),
        SloRule("post_warmup_recompiles", "recompile_events_total", 0,
                description="XLA compiles after engine warmup"),
    ]


def default_fleet_rules(
    *,
    pressure: float = 0.85,
    min_up_replicas: float = 1,
    ttft_p99_s: float = 1.0,
    sustain_s: float = 5.0,
) -> list:
    """Fleet-router SLOs over the gauges ``serve/fleet`` maintains:
    sustained demand beyond up-capacity (the scale-UP signal), the
    healthy-replica floor (instant — zero up replicas is an outage, not a
    trend), and routed tail TTFT as the user-visible latency objective."""
    return [
        SloRule("fleet_pressure", "fleet_pressure", pressure,
                sustain_s=sustain_s,
                description="demand vs up-replica slot capacity"),
        SloRule("fleet_up_replicas", "fleet_up_replicas", min_up_replicas,
                direction="below",
                description="healthy replica floor"),
        SloRule("fleet_ttft_p99", "fleet_ttft_seconds", ttft_p99_s,
                aggregation="p99", sustain_s=sustain_s,
                description="router-observed p99 time-to-first-token"),
    ]


def default_training_rules(
    *,
    step_seconds: float = 10.0,
    data_wait_frac: float = 0.5,
    sustain_s: float = 0.0,
) -> list:
    """Training-side SLOs: a step-time ceiling (hung collectives / thrashing
    show up here first) and an input-bound alarm on the measured data-wait
    share of the window."""
    return [
        SloRule("step_time", "train_step_seconds", step_seconds,
                sustain_s=sustain_s,
                description="mean seconds per optimizer step"),
        SloRule("data_wait", "train_data_wait_frac", data_wait_frac,
                sustain_s=sustain_s,
                description="fraction of window blocked on input"),
    ]


class SloMonitor:
    """Evaluates rules against a registry; keeps per-rule breach state.

    Thread-safe: the ticker thread, an HTTP handler rendering
    ``/slo.json``, and a manual ``evaluate()`` may interleave. Callbacks
    run inline on the evaluating thread and must be quick; a raising
    callback is swallowed (the metrics plane must not take down the
    serving plane)."""

    def __init__(self, registry=None, rules=(), *, clock=time.monotonic,
                 recorder=None):
        self._registry = registry
        self._clock = clock
        self._recorder = recorder
        self._lock = threading.Lock()
        self._rules: list[SloRule] = []
        self._state: dict[str, dict] = {}
        self._callbacks: list = []
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        reg = registry if registry is not None else _registry.get_registry()
        self._breach_total = reg.counter(
            "slo_breach_total", "SLO ok->breach transitions.",
            labels=("rule",))
        for r in rules:
            self.add_rule(r)

    # -- configuration ----------------------------------------------------

    def add_rule(self, rule: SloRule) -> None:
        with self._lock:
            if any(r.name == rule.name for r in self._rules):
                raise ValueError(f"duplicate SLO rule name {rule.name!r}")
            self._rules.append(rule)
            self._state[rule.name] = {
                "status": "no_data", "value": None, "since": None,
                "breaches": 0, "last_transition": None,
            }

    def add_callback(self, fn) -> None:
        """``fn(rule: SloRule, status: str, value: float)`` on every
        ok↔breach transition — the autoscaling/drain hook."""
        with self._lock:
            self._callbacks.append(fn)

    @property
    def rules(self) -> list:
        with self._lock:
            return list(self._rules)

    # -- evaluation -------------------------------------------------------

    def _resolve(self, rule: SloRule):
        """Current aggregated reading for a rule, or None (no data)."""
        reg = (self._registry if self._registry is not None
               else _registry.get_registry())
        fam = None
        for f in reg.collect():
            if f.name == rule.metric:
                fam = f
                break
        if fam is None:
            return None
        insts = []
        for label_values, inst in fam.children():
            if rule.labels:
                got = dict(zip(fam.label_names, label_values))
                if any(got.get(k) != v for k, v in rule.labels.items()):
                    continue
            insts.append(inst)
        if not insts:
            return None
        if fam.kind == "histogram":
            if rule.aggregation in ("p50", "p95", "p99"):
                q = float(rule.aggregation[1:])
                vals = [i.percentile(q) for i in insts if i.count]
                return max(vals) if vals else None
            summaries = [i.summary() for i in insts]
            total_count = sum(s["count"] for s in summaries)
            if rule.aggregation == "count":
                return float(total_count)
            if total_count == 0:
                return None
            if rule.aggregation == "max":
                return max(s["max"] for s in summaries)
            # mean / value: lifetime-weighted mean across children.
            return (sum(s["mean"] * s["count"] for s in summaries)
                    / total_count)
        values = [i.value for i in insts]
        if rule.aggregation == "max":
            return max(values)
        if fam.kind == "counter" or rule.aggregation in ("count", "mean"):
            total = sum(values)
            return total / len(values) if rule.aggregation == "mean" else total
        # Gauges aggregate by max: the worst replica is the honest fleet
        # reading for a threshold alarm.
        return max(values)

    def evaluate(self) -> dict:
        """One evaluation pass over every rule; returns :meth:`status`."""
        now = self._clock()
        transitions = []
        with self._lock:
            rules = list(self._rules)
        for rule in rules:
            value = self._resolve(rule)
            with self._lock:
                st = self._state[rule.name]
                if value is None:
                    if st["status"] not in ("breach",):
                        st["status"] = "no_data"
                    st["value"] = None
                    continue
                bad = (value > rule.threshold if rule.direction == "above"
                       else value < rule.threshold)
                st["value"] = value
                if bad:
                    if st["status"] in ("ok", "no_data"):
                        st["since"] = now
                        st["status"] = "pending"
                    if (st["status"] == "pending"
                            and now - st["since"] >= rule.sustain_s):
                        st["status"] = "breach"
                        st["breaches"] += 1
                        st["last_transition"] = now
                        transitions.append((rule, "breach", value))
                else:
                    if st["status"] == "breach":
                        st["last_transition"] = now
                        transitions.append((rule, "ok", value))
                    st["status"] = "ok"
                    st["since"] = None
        for rule, status, value in transitions:
            self._emit(rule, status, value)
        return self.status()

    def _emit(self, rule: SloRule, status: str, value: float) -> None:
        if status == "breach":
            self._breach_total.labels(rule.name).inc()
        event = "slo_breach" if status == "breach" else "slo_recovered"
        _trace.trace_event(
            event, rule=rule.name, metric=rule.metric,
            aggregation=rule.aggregation, value=value,
            threshold=rule.threshold, direction=rule.direction,
        )
        rec = (self._recorder if self._recorder is not None
               else _recorder.get_recorder())
        rec.record(kind="event", name=event, rule=rule.name,
                   metric=rule.metric, value=value,
                   threshold=rule.threshold)
        with self._lock:
            callbacks = list(self._callbacks)
        for fn in callbacks:
            try:
                fn(rule, status, value)
            except Exception:  # noqa: BLE001 — see class docstring
                pass

    # -- readout ----------------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return any(s["status"] == "breach" for s in self._state.values())

    def status(self) -> dict:
        """JSON-ready: overall degraded flag + per-rule state (what
        ``GET /slo.json`` serves)."""
        with self._lock:
            rules = {
                r.name: {
                    "status": self._state[r.name]["status"],
                    "value": self._state[r.name]["value"],
                    "breaches": self._state[r.name]["breaches"],
                    "metric": r.metric,
                    "aggregation": r.aggregation,
                    "threshold": r.threshold,
                    "direction": r.direction,
                    "sustain_s": r.sustain_s,
                    "description": r.description,
                }
                for r in self._rules
            }
        return {
            "degraded": any(v["status"] == "breach" for v in rules.values()),
            "num_rules": len(rules),
            "rules": rules,
        }

    # -- ticker -----------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> None:
        """Evaluate on a daemon thread every ``interval_s`` seconds."""
        if self._ticker is not None:
            raise RuntimeError("SLO ticker already started")
        self._stop.clear()

        def tick():
            while not self._stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 — keep ticking
                    pass

        self._ticker = threading.Thread(
            target=tick, name="slo-monitor", daemon=True)
        self._ticker.start()

    def stop(self, timeout: float = 5.0) -> None:
        if self._ticker is None:
            return
        self._stop.set()
        self._ticker.join(timeout)
        self._ticker = None
