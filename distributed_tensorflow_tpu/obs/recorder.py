"""Flight recorder: fixed-size in-memory ring of the last N spans/events.

A multi-host incident rarely leaves usable evidence: stdout is interleaved,
TensorBoard events flush late, and the interesting part is the last few
seconds before the SIGTERM/exception. The recorder keeps a bounded deque of
recent telemetry (closed spans from :mod:`~distributed_tensorflow_tpu.obs.trace`,
plus instantaneous events) and dumps it as JSONL when something goes wrong:

* ``train/resilience.py`` calls :func:`FlightRecorder.dump` on preemption and
  rollback;
* :func:`install_excepthook` chains onto ``sys.excepthook`` so ANY unhandled
  exception in an obs-enabled process ships its timeline.

Recording cost is one lock + deque.append (the deque is bounded, so memory is
fixed). Dumping is the only I/O, and it only happens on the failure path.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any

__all__ = [
    "FlightRecorder",
    "get_recorder",
    "set_recorder",
    "set_dump_dir",
    "install_excepthook",
]

DEFAULT_CAPACITY = 1024


class FlightRecorder:
    """Bounded ring buffer of telemetry events (dicts). Thread-safe."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(maxlen=capacity)
        self._seq = 0

    def record(self, **event: Any) -> None:
        """Append one event. A monotonically increasing ``seq`` and a wall
        timestamp are stamped here so dump ordering is unambiguous even when
        two events land within clock resolution."""
        with self._lock:
            self._seq += 1
            event.setdefault("seq", self._seq)
            event.setdefault("t_wall", time.time())
            event.setdefault("t_mono", time.monotonic())
            self._events.append(event)

    def record_span(self, sp) -> None:
        """Entry point for :class:`~distributed_tensorflow_tpu.obs.trace.Span`
        — converts to a dict event (keeps the recorder span-class agnostic)."""
        ev = sp.to_event()
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def events(self) -> list[dict]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: str, *, reason: str = "") -> str:
        """Write the ring to ``path`` as JSONL (one event per line, oldest
        first), prefixed with a header line identifying the dump. Returns the
        path. Creates parent directories. Never raises on serialization —
        unserializable attrs are stringified (a crash dump must not crash)."""
        events = self.events()
        header = {
            "kind": "flight_record",
            "reason": reason,
            "pid": os.getpid(),
            "t_wall": time.time(),
            "num_events": len(events),
            "capacity": self.capacity,
        }
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
        return path


_recorder_lock = threading.Lock()
_recorder = FlightRecorder()
_dump_dir: str = ""


def get_recorder() -> FlightRecorder:
    return _recorder


def set_recorder(recorder: FlightRecorder) -> None:
    global _recorder
    with _recorder_lock:
        _recorder = recorder


def set_dump_dir(path: str) -> None:
    """Where crash dumps land (``--obs_dir``). Empty disables dumping — the
    ring still records, but :func:`dump_to_dir` becomes a no-op."""
    global _dump_dir
    _dump_dir = path


def get_dump_dir() -> str:
    return _dump_dir


def dump_to_dir(reason: str) -> str | None:
    """Dump the process recorder into the configured dump dir, named
    ``flight_<reason>_p<process>_<pid>.jsonl``. Returns the path, or None
    when no dump dir is configured. Best-effort: I/O errors are swallowed
    (this runs on failure paths where a second exception helps nobody)."""
    if not _dump_dir:
        return None
    proc = 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            proc = int(jax.process_index())
        except Exception:  # noqa: BLE001
            proc = 0
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
    path = os.path.join(
        _dump_dir, f"flight_{safe_reason}_p{proc}_{os.getpid()}.jsonl"
    )
    try:
        return _recorder.dump(path, reason=reason)
    except OSError:
        return None


_hook_installed = False


def install_excepthook() -> None:
    """Chain a flight-record dump onto ``sys.excepthook`` AND
    ``threading.excepthook`` so any unhandled exception — main thread or a
    background one (checkpoint snapshot thread, scheduler loop) — writes
    its timeline before dying. Without the threading hook, a crashing
    daemon thread evaporates silently with no dump. Idempotent; the
    previous hooks (usually the default traceback printers) still run."""
    global _hook_installed
    with _recorder_lock:
        if _hook_installed:
            return
        _hook_installed = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            get_recorder().record(
                kind="event",
                name="unhandled_exception",
                error=f"{exc_type.__name__}: {exc}",
            )
            dump_to_dir("unhandled_exception")
        finally:
            prev(exc_type, exc, tb)

    sys.excepthook = _hook

    prev_threading = threading.excepthook

    def _thread_hook(args):
        try:
            get_recorder().record(
                kind="event",
                name="unhandled_thread_exception",
                thread=getattr(args.thread, "name", None),
                error=f"{args.exc_type.__name__}: {args.exc_value}",
            )
            dump_to_dir("unhandled_thread_exception")
        finally:
            prev_threading(args)

    threading.excepthook = _thread_hook
