"""Live performance accounting: MFU/throughput gauges, memory watermarks,
and the recompile sentinel.

``utils/flops.py`` already knows the model-FLOP and roofline math, but until
now it only fed offline ``bench.py`` records and boundary stdout prints.
This module turns the same arithmetic into registry gauges refreshed every
eval window, so a scraper sees the fleet's compute efficiency live:

* :class:`PerfGauges` — ``train_mfu`` (model FLOPs x steps/s over cluster
  peak; absent off-TPU, where ``chip_peak_flops`` correctly refuses to
  invent a denominator), ``tokens_per_second`` / ``examples_per_second``,
  and ``train_step_seconds`` (the SLO monitor's step-time selector).
* :func:`update_memory_gauges` — per-device ``bytes_in_use`` /
  ``peak_bytes_in_use`` watermarks from ``Device.memory_stats()``. The CPU
  backend returns None there; the gauges are then simply not touched
  (graceful null — no fake zeros in the scrape).
* :class:`RecompileSentinel` — the serving engine's zero-recompile-after-
  warmup invariant was a test-only ``compile_count()`` assert; this makes
  it an ALERTING runtime metric. Primary signal: a ``jax.monitoring``
  event-duration listener on ``backend_compile`` events (fires once per
  XLA compilation). jax 0.4.x has no per-listener unregister (only a
  global ``clear_event_listeners``), so ONE module-level dispatcher is
  registered process-wide on first use and forwards to whichever sentinels
  are currently open — ``close()`` detaches a sentinel without touching
  the global listener list. Version-guarded fallback: when the monitoring
  API is missing (or listener mode is explicitly declined), the sentinel
  counts deltas of an externally-polled compile-cache size
  (``SlotEngine.compile_count()`` feeds :meth:`RecompileSentinel.poll`
  every engine round). ``mark_warm()`` draws the line: compile events
  before it are warmup, events after it increment
  ``recompile_events_total`` — the metric the default serving SLO rule
  alerts on (threshold 0: ANY post-warmup compile is a breach).
"""

from __future__ import annotations

import threading

from distributed_tensorflow_tpu.obs import registry as _registry

__all__ = [
    "PerfGauges",
    "update_memory_gauges",
    "RecompileSentinel",
    "monitoring_available",
]


# ---------------------------------------------------------------------------
# throughput / MFU gauges
# ---------------------------------------------------------------------------


class PerfGauges:
    """Eval-window performance gauges on a registry (process default when
    ``registry`` is None). Call :meth:`update_window` at each boundary with
    whatever is known; unknown quantities leave their gauges untouched."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else _registry.get_registry()
        self.mfu = reg.gauge(
            "train_mfu",
            "Model FLOPs utilization over the last drained window "
            "(absent off-TPU: no peak to divide by).")
        self.tokens_rate = reg.gauge(
            "tokens_per_second", "Global tokens/s over the last window.")
        self.examples_rate = reg.gauge(
            "examples_per_second", "Global examples/s over the last window.")
        self.step_seconds = reg.gauge(
            "train_step_seconds",
            "Mean seconds per optimizer step over the last window.")

    def update_window(
        self,
        *,
        steps_per_sec: float,
        tokens_per_step: int | None = None,
        examples_per_step: int | None = None,
        model_cfg=None,
        batch_size: int | None = None,
        seq_len: int | None = None,
        flops_per_step: float | None = None,
        peak_flops: float | None = None,
        num_devices: int | None = None,
    ) -> float | None:
        """Refresh rates for one drained window; returns the MFU (or None
        when it cannot be computed — off-TPU, or no model math given).

        MFU numerator: ``flops_per_step`` directly, else
        ``transformer_train_flops(model_cfg, batch_size, seq_len)``.
        Denominator: ``peak_flops`` per device (default
        ``chip_peak_flops()``) x ``num_devices`` (default all)."""
        if steps_per_sec <= 0:
            return None  # compile window — rates would be lies
        self.step_seconds.set(1.0 / steps_per_sec)
        if tokens_per_step:
            self.tokens_rate.set(steps_per_sec * tokens_per_step)
        if examples_per_step:
            self.examples_rate.set(steps_per_sec * examples_per_step)
        flops = flops_per_step
        if flops is None and model_cfg is not None and batch_size:
            from distributed_tensorflow_tpu.utils.flops import (
                transformer_train_flops,
            )

            flops = transformer_train_flops(model_cfg, batch_size, seq_len)
        if flops is None:
            return None
        if peak_flops is None:
            from distributed_tensorflow_tpu.utils.flops import chip_peak_flops

            peak_flops = chip_peak_flops()
        if peak_flops is None:
            return None  # graceful null: no invented denominator
        if num_devices is None:
            import jax

            num_devices = len(jax.devices())
        mfu = flops * steps_per_sec / (peak_flops * max(num_devices, 1))
        self.mfu.set(mfu)
        return mfu


def update_memory_gauges(registry=None) -> dict:
    """Refresh per-device HBM watermark gauges from
    ``Device.memory_stats()``. Returns ``{device_label: stats}`` for the
    devices that reported; empty on backends (CPU) whose ``memory_stats()``
    is None or missing — the graceful-null contract: gauges untouched, no
    zeros invented."""
    import jax

    reg = registry if registry is not None else _registry.get_registry()
    in_use = reg.gauge(
        "device_memory_bytes_in_use",
        "Live device allocation (memory_stats bytes_in_use).",
        labels=("device",))
    peak = reg.gauge(
        "device_memory_peak_bytes",
        "High-watermark device allocation this process lifetime.",
        labels=("device",))
    limit = reg.gauge(
        "device_memory_limit_bytes",
        "Allocator capacity (memory_stats bytes_limit).",
        labels=("device",))
    out: dict = {}
    for dev in jax.local_devices():
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API at all
            stats = None
        if not stats:
            continue
        label = f"{dev.platform}:{dev.id}"
        if "bytes_in_use" in stats:
            in_use.labels(label).set(float(stats["bytes_in_use"]))
        if "peak_bytes_in_use" in stats:
            peak.labels(label).set(float(stats["peak_bytes_in_use"]))
        if "bytes_limit" in stats:
            limit.labels(label).set(float(stats["bytes_limit"]))
        out[label] = stats
    return out


# ---------------------------------------------------------------------------
# recompile sentinel
# ---------------------------------------------------------------------------

_dispatch_lock = threading.Lock()
_dispatch_installed = False
_active_sentinels: list["RecompileSentinel"] = []


def monitoring_available() -> bool:
    """Version guard: does this jax expose the event-duration listener the
    sentinel's primary signal needs?"""
    try:
        from jax import monitoring  # noqa: F401

        return callable(getattr(monitoring, "register_event_duration_secs_listener", None))
    except Exception:  # noqa: BLE001
        return False


def _dispatch(event: str, duration=None, **kw) -> None:
    # One XLA compilation records exactly one backend_compile duration;
    # the jaxpr-trace/MLIR-lowering events around it would double count.
    if "backend_compile" not in event:
        return
    with _dispatch_lock:
        targets = list(_active_sentinels)
    for s in targets:
        s._on_compile_event()


def _ensure_dispatcher() -> bool:
    """Register the process-wide listener once (jax 0.4.x cannot unregister
    a single listener, so it is never removed — it forwards to the
    currently-open sentinels only)."""
    global _dispatch_installed
    with _dispatch_lock:
        if _dispatch_installed:
            return True
        if not monitoring_available():
            return False
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_dispatch)
        _dispatch_installed = True
        return True


class RecompileSentinel:
    """Counts XLA compile events at runtime and alerts on any after warmup.

    Metrics (on ``registry``, process default when None):

    * ``xla_compile_events_total`` — every compile seen since install.
    * ``recompile_events_total`` — compiles AFTER :meth:`mark_warm`; the
      zero-recompile invariant says this stays 0 forever, so the default
      serving SLO rule breaches on value > 0.

    ``mode`` is ``"listener"`` when the jax.monitoring dispatcher is live
    (process-wide events), ``"poll"`` when falling back to cache-size
    deltas fed through :meth:`poll`. In listener mode ``poll()`` is a
    no-op so the two signals never double count.
    """

    def __init__(self, registry=None, *, use_listener: bool = True):
        reg = registry if registry is not None else _registry.get_registry()
        self._compiles = reg.counter(
            "xla_compile_events_total",
            "XLA compilations observed by the recompile sentinel.")
        self._post_warm = reg.counter(
            "recompile_events_total",
            "XLA compilations observed AFTER warmup — must stay 0.")
        self._lock = threading.Lock()
        self._warm = False
        self._poll_base: int | None = None
        self.mode = "poll"
        if use_listener and _ensure_dispatcher():
            self.mode = "listener"
            with _dispatch_lock:
                _active_sentinels.append(self)

    # -- signal paths -----------------------------------------------------

    def _on_compile_event(self) -> None:
        with self._lock:
            warm = self._warm
        self._compiles.inc()
        if warm:
            self._post_warm.inc()

    def poll(self, compile_count: int) -> None:
        """Fallback feed: an externally-observed monotone compile-cache
        size (e.g. ``SlotEngine.compile_count()``). Deltas become events.
        No-op in listener mode (the listener already saw them)."""
        if self.mode == "listener":
            return
        with self._lock:
            base, self._poll_base = self._poll_base, int(compile_count)
            warm = self._warm
        if base is None:
            return
        delta = int(compile_count) - base
        if delta > 0:
            self._compiles.inc(delta)
            if warm:
                self._post_warm.inc(delta)

    def mark_warm(self) -> None:
        """Everything compiled so far was warmup; anything after this is a
        recompile (the alert condition)."""
        with self._lock:
            self._warm = True

    def close(self) -> None:
        """Detach from the process-wide dispatcher (the listener itself
        stays registered — jax 0.4.x has no unregister)."""
        with _dispatch_lock:
            if self in _active_sentinels:
                _active_sentinels.remove(self)

    # -- readout ----------------------------------------------------------

    @property
    def events_total(self) -> int:
        return int(self._compiles.value)

    @property
    def post_warm_total(self) -> int:
        return int(self._post_warm.value)
