"""Thread-safe process-wide metrics registry (Prometheus-style instruments).

Three instrument kinds, each addressable as a labeled *family*:

* :class:`Counter` — monotonically increasing float (``_total`` names by
  convention); resets only with the process.
* :class:`Gauge` — a value that goes both ways (queue depth, occupancy).
* :class:`Histogram` — bounded reservoir for percentile readout (serving
  metrics should reflect CURRENT behavior, not the warmup transient from an
  hour ago) plus exact lifetime ``count``/``sum`` and cumulative bucket
  counts for the Prometheus exposition.

Concurrency contract: every mutation and every read snapshot takes the
instrument's own lock, so a ThreadingHTTPServer handler thread can render
``/metrics`` while the scheduler thread ``observe()``s — the exact race
that crashed the old ``serve/metrics.py`` deque (append during iteration).
The hot-path cost is one uncontended lock acquire + a float op, which is
what keeps the bench.py overhead gate (≤1% vs no-op) honest rather than
lucky.

Registration is idempotent: asking for an existing (name, kind) returns the
same family; re-registering a name as a different kind raises. A
:class:`NullRegistry` hands out shared no-op instruments so ``obs.disable()``
turns every call site into a near-free method call.
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Iterable, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Family",
    "MetricsRegistry",
    "NullRegistry",
    "get_registry",
    "set_registry",
    "DEFAULT_BUCKETS",
]

# Geometric 1-2.5-5 ladder from 1 ms to 10 s — wide enough for TTFT,
# per-token gaps, step times, and checkpoint stalls without per-site tuning.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """np.percentile's default linear interpolation, numpy-free."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_vals[0])
    pos = (q / 100.0) * (n - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


class Counter:
    """Monotonic accumulator. ``inc`` with a negative amount raises — a
    shrinking counter means a bug at the call site, not a feature."""

    kind = "counter"
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    kind = "gauge"
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded reservoir (most recent ``maxlen`` samples, deque semantics)
    with exact lifetime ``count``/``total`` and cumulative bucket counts.

    All reads snapshot under the same lock the writes take — ``percentile``
    / ``summary`` / ``values`` are safe against a concurrent ``observe``
    from another thread (the old serve Histogram's
    "deque mutated during iteration" crash is structurally impossible here).
    """

    kind = "histogram"

    def __init__(self, maxlen: int = 4096, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self._samples: deque[float] = deque(maxlen=maxlen)
        self._buckets = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * len(self._buckets)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._samples.append(value)
            self.count += 1
            self.total += value
            i = bisect.bisect_left(self._buckets, value)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1

    def _snapshot(self) -> list[float]:
        with self._lock:
            return list(self._samples)

    def percentile(self, q: float) -> float:
        """q in [0, 100] over the reservoir; 0.0 with no samples."""
        return _percentile(sorted(self._snapshot()), q)

    def summary(self) -> dict:
        with self._lock:
            vals = sorted(self._samples)
            count, total = self.count, self.total
        return {
            "count": count,
            "mean": total / count if count else 0.0,
            "p50": _percentile(vals, 50),
            "p95": _percentile(vals, 95),
            "p99": _percentile(vals, 99),
            "max": vals[-1] if vals else 0.0,
        }

    def values(self):
        """Reservoir contents as a float64 numpy array (for
        ``SummaryWriter.add_histogram``)."""
        import numpy as np

        return np.asarray(self._snapshot(), np.float64)

    def buckets(self) -> list[tuple[float, int]]:
        """CUMULATIVE (le, count) pairs, Prometheus ``_bucket`` semantics;
        the implicit +Inf bucket is the lifetime count."""
        with self._lock:
            counts = list(self._bucket_counts)
        out, acc = [], 0
        for le, c in zip(self._buckets, counts):
            acc += c
            out.append((le, acc))
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One registered metric name: either a single unlabeled instrument or a
    set of labeled children. Unlabeled families proxy the instrument API
    directly (``registry.counter("x").inc()``); labeled families hand out
    children via :meth:`labels`."""

    def __init__(self, name: str, kind: str, help: str,
                 label_names: tuple[str, ...], make):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = label_names
        self._make = make
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not label_names:
            self._children[()] = make()

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(str(kv[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make()
            return child

    def children(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    # -- unlabeled proxy ---------------------------------------------------

    def _solo(self):
        if self.label_names:
            raise ValueError(f"{self.name} is labeled — use .labels(...)")
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    @property
    def value(self) -> float:
        return self._solo().value

    def percentile(self, q: float) -> float:
        return self._solo().percentile(q)

    def summary(self) -> dict:
        return self._solo().summary()

    def values(self):
        return self._solo().values()

    def buckets(self):
        return self._solo().buckets()

    @property
    def count(self) -> int:
        return self._solo().count

    @property
    def total(self) -> float:
        return self._solo().total


class MetricsRegistry:
    """Process-wide (or scoped — serving builds a private one per stack so
    tests stay isolated) family registry. Registration is idempotent per
    (name, kind); kind conflicts raise immediately."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}

    def _register(self, name: str, kind: str, help: str,
                  labels: Iterable[str], make) -> Family:
        labels = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"not {kind}"
                    )
                return fam
            fam = self._families[name] = Family(name, kind, help, labels, make)
            return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Family:
        return self._register(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Family:
        return self._register(name, "gauge", help, labels, Gauge)

    def histogram(self, name: str, help: str = "", labels: Iterable[str] = (),
                  maxlen: int = 4096,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Family:
        return self._register(
            name, "histogram", help, labels,
            lambda: Histogram(maxlen=maxlen, buckets=buckets),
        )

    def collect(self) -> list[Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)


class _NullInstrument:
    """Shared do-nothing instrument: every mutator is a constant-time no-op,
    every reader returns zeros. ``labels()`` returns itself so labeled call
    sites need no special casing."""

    kind = "null"
    count = 0
    total = 0.0
    value = 0.0

    def labels(self, *a, **k):
        return self

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def percentile(self, q: float) -> float:
        return 0.0

    def summary(self) -> dict:
        return {"count": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0,
                "p99": 0.0, "max": 0.0}

    def values(self):
        import numpy as np

        return np.zeros(0, np.float64)

    def buckets(self) -> list:
        return []


_NULL = _NullInstrument()


class NullRegistry:
    """The obs-disabled registry: all three constructors return one shared
    no-op instrument (the bench.py overhead baseline)."""

    def counter(self, name: str, help: str = "", labels=()) -> _NullInstrument:
        return _NULL

    def gauge(self, name: str, help: str = "", labels=()) -> _NullInstrument:
        return _NULL

    def histogram(self, name: str, help: str = "", labels=(), maxlen: int = 4096,
                  buckets=DEFAULT_BUCKETS) -> _NullInstrument:
        return _NULL

    def collect(self) -> list:
        return []


_default_lock = threading.Lock()
_default: MetricsRegistry | NullRegistry = MetricsRegistry()


def get_registry() -> MetricsRegistry | NullRegistry:
    """The process default registry (what the train loops and the data
    pipeline publish into)."""
    return _default


def set_registry(registry) -> None:
    global _default
    with _default_lock:
        _default = registry
