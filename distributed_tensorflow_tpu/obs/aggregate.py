"""Cross-process metric aggregation: N per-process registries, one fleet view.

PR 5 gave every process its own registry and scrape surface; a multi-host
run therefore exposes N disjoint ``/metrics`` pages a human has to correlate
by hand. This module merges them with per-kind semantics:

* **counters sum** — ``train_steps_total`` over the fleet is the sum of the
  per-process totals (same label set → one merged child).
* **gauges keep process identity** — a gauge is a point-in-time reading, so
  summing ``train_examples_per_sec`` across processes and ``serve_queue_depth_current``
  across replicas means different things. The merged family gets a
  ``process`` label prepended to the original labels (one child per source
  process) plus ``<name>_min`` / ``<name>_max`` / ``<name>_sum`` rollup
  gauges over the original label sets, so both the per-replica view and the
  fleet aggregate are one selector away.
* **histograms merge exactly where they can** — per-bucket counts and the
  lifetime count/total are exact lifetime accounting, so identical bucket
  ladders merge by addition. The bounded reservoirs (recent-percentile
  readout) are SUBSAMPLED: each process contributes a share of the merged
  reservoir proportional to its sample count, taken evenly over its
  reservoir (deterministic — no RNG in the metrics plane). A ladder
  mismatch (processes running different code) falls back to re-bucketing
  the reservoirs only; count/total stay exact either way.

Feeding is either **explicit push** (:meth:`FleetAggregator.push` with a
:func:`full_snapshot` dict — the in-process path a router tier will use) or
**file-fed** through a shared ``--obs_dir``: every process drops an atomic
``fleet_p<i>.json`` (:func:`write_process_snapshot`), the chief loads the
directory and exports the merged registry as Prometheus text + JSON
(:meth:`FleetAggregator.export`). The file path is what the multi-process
CPU tests exercise — no network needed, the shared filesystem IS the
transport, exactly like the checkpoint manifests.

:func:`full_snapshot` exists because :func:`export.registry_snapshot`
reduces histograms to summary dicts — enough for humans, not enough to
merge. This one carries the exact bucket counts and the reservoir, i.e.
everything needed to reconstruct the instrument on the other side.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from collections import deque

from distributed_tensorflow_tpu.obs import export as _export
from distributed_tensorflow_tpu.obs.registry import MetricsRegistry

__all__ = [
    "full_snapshot",
    "write_process_snapshot",
    "load_process_snapshots",
    "merge_snapshots",
    "FleetAggregator",
]

_SNAPSHOT_PREFIX = "fleet_p"


def _process_index() -> int:
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 — uninitialized backend
            return 0
    return 0


def full_snapshot(registry=None, *, process: int | None = None) -> dict:
    """Aggregation-grade snapshot: everything needed to merge, per family.

    Counters/gauges carry ``value``; histograms carry the exact
    ``bucket_les``/``bucket_counts`` (non-cumulative), lifetime
    ``count``/``total``, the reservoir contents, and ``maxlen``. Label
    values are stored as lists (JSON has no tuples)."""
    from distributed_tensorflow_tpu.obs import registry as _registry

    registry = registry if registry is not None else _registry.get_registry()
    proc = _process_index() if process is None else int(process)
    out: dict = {
        "process": proc,
        "pid": os.getpid(),
        "t_wall": time.time(),
        "metrics": {},
    }
    for fam in registry.collect():
        samples = []
        for label_values, inst in fam.children():
            entry: dict = {"labels": list(label_values)}
            if fam.kind == "histogram":
                with inst._lock:
                    entry.update(
                        count=inst.count,
                        total=inst.total,
                        bucket_les=list(inst._buckets),
                        bucket_counts=list(inst._bucket_counts),
                        reservoir=list(inst._samples),
                        maxlen=inst._samples.maxlen,
                    )
            else:
                entry["value"] = inst.value
            samples.append(entry)
        out["metrics"][fam.name] = {
            "kind": fam.kind,
            "help": fam.help,
            "label_names": list(fam.label_names),
            "samples": samples,
        }
    return out


def write_process_snapshot(obs_dir: str, registry=None, *,
                           process: int | None = None) -> str:
    """Atomically write this process's :func:`full_snapshot` to
    ``<obs_dir>/fleet_p<process>.json`` (tmp + rename, so a concurrent
    chief read never sees a torn file). Returns the path."""
    snap = full_snapshot(registry, process=process)
    os.makedirs(obs_dir, exist_ok=True)
    path = os.path.join(obs_dir, f"{_SNAPSHOT_PREFIX}{snap['process']}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(snap, default=str))
    os.replace(tmp, path)
    return path


def load_process_snapshots(obs_dir: str) -> list[dict]:
    """All ``fleet_p*.json`` snapshots in ``obs_dir``, ordered by process
    index. Torn/unparseable files are skipped (the writer is atomic, but a
    crashed process may have left a stale ``.tmp``)."""
    snaps = []
    for path in sorted(glob.glob(os.path.join(obs_dir, f"{_SNAPSHOT_PREFIX}*.json"))):
        try:
            with open(path) as f:
                snaps.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    snaps.sort(key=lambda s: int(s.get("process", 0)))
    return snaps


def _subsample(values: list[float], k: int) -> list[float]:
    """Evenly-spaced deterministic pick of k items (all of them if k >= n)."""
    n = len(values)
    if k >= n:
        return list(values)
    if k <= 0:
        return []
    # Even stride over the index range keeps the tail (most recent) samples.
    return [values[(i * n) // k] for i in range(k)]


def _merge_histogram(inst, samples: list[dict]) -> None:
    """Install the merged state of per-process histogram ``samples`` into a
    fresh registry ``Histogram`` instance. Exact count/total always; exact
    bucket addition when every ladder matches the instrument's; reservoirs
    subsampled proportionally to each process's lifetime count."""
    count = sum(int(s["count"]) for s in samples)
    total = sum(float(s["total"]) for s in samples)
    ladders_match = all(
        tuple(float(b) for b in s["bucket_les"]) == inst._buckets
        for s in samples
    )
    if ladders_match:
        bucket_counts = [0] * len(inst._buckets)
        for s in samples:
            for i, c in enumerate(s["bucket_counts"]):
                bucket_counts[i] += int(c)
    else:
        # Different code revisions on different processes: re-bucket what we
        # still have (the reservoirs). Approximate by construction — the
        # exact per-bucket history of the mismatched ladder is gone.
        import bisect

        bucket_counts = [0] * len(inst._buckets)
        for s in samples:
            for v in s["reservoir"]:
                i = bisect.bisect_left(inst._buckets, float(v))
                if i < len(bucket_counts):
                    bucket_counts[i] += 1
    maxlen = inst._samples.maxlen
    weights = [max(int(s["count"]), len(s["reservoir"])) for s in samples]
    total_w = sum(weights) or 1
    merged_reservoir: list[float] = []
    for s, w in zip(samples, weights):
        share = min(len(s["reservoir"]),
                    max(1 if s["reservoir"] else 0, (maxlen * w) // total_w))
        merged_reservoir.extend(_subsample([float(v) for v in s["reservoir"]],
                                           share))
    with inst._lock:
        inst.count = count
        inst.total = total
        inst._bucket_counts = bucket_counts
        inst._samples = deque(merged_reservoir[-maxlen:], maxlen=maxlen)


def merge_snapshots(snapshots: list[dict]) -> MetricsRegistry:
    """Merge per-process :func:`full_snapshot` dicts into one fleet
    registry (per-kind semantics in the module docstring)."""
    merged = MetricsRegistry()
    # name -> kind/help/label_names from the first snapshot that has it;
    # per (name, labels) accumulation across processes.
    for name in sorted({n for s in snapshots for n in s["metrics"]}):
        metas = [(s, s["metrics"][name]) for s in snapshots
                 if name in s["metrics"]]
        first = metas[0][1]
        kind = first["kind"]
        help_ = first.get("help", "")
        label_names = tuple(first.get("label_names", ()))
        if kind == "counter":
            fam = merged.counter(name, help_, labels=label_names)
            acc: dict[tuple, float] = {}
            for _, m in metas:
                for smp in m["samples"]:
                    key = tuple(smp["labels"])
                    acc[key] = acc.get(key, 0.0) + float(smp["value"])
            for key, v in acc.items():
                (fam.labels(*key) if label_names else fam._solo()).inc(v)
        elif kind == "gauge":
            fam = merged.gauge(name, help_,
                               labels=("process",) + label_names)
            rollup: dict[tuple, list[float]] = {}
            for snap, m in metas:
                proc = str(snap.get("process", 0))
                for smp in m["samples"]:
                    v = float(smp["value"])
                    fam.labels(proc, *smp["labels"]).set(v)
                    rollup.setdefault(tuple(smp["labels"]), []).append(v)
            for suffix, agg in (("min", min), ("max", max), ("sum", sum)):
                rfam = merged.gauge(
                    f"{name}_{suffix}",
                    f"{suffix} of {name} across processes.",
                    labels=label_names)
                for key, vals in rollup.items():
                    inst = rfam.labels(*key) if label_names else rfam._solo()
                    inst.set(agg(vals))
        else:  # histogram
            by_labels: dict[tuple, list[dict]] = {}
            for _, m in metas:
                for smp in m["samples"]:
                    by_labels.setdefault(tuple(smp["labels"]), []).append(smp)
            any_smp = next(iter(by_labels.values()))[0]
            fam = merged.histogram(
                name, help_, labels=label_names,
                maxlen=int(any_smp.get("maxlen") or 4096),
                buckets=tuple(float(b) for b in any_smp["bucket_les"]),
            )
            for key, smps in by_labels.items():
                inst = fam.labels(*key) if label_names else fam._solo()
                _merge_histogram(inst, smps)
    return merged


class FleetAggregator:
    """Chief-side collector: push or load per-process snapshots, read out
    the merged fleet registry, export it next to the inputs."""

    def __init__(self):
        self._snaps: dict[int, dict] = {}

    def push(self, snapshot: dict) -> None:
        """Explicit-push feed (in-process / future router RPC path). Later
        pushes for the same process index replace earlier ones."""
        self._snaps[int(snapshot.get("process", 0))] = snapshot

    def load_dir(self, obs_dir: str) -> int:
        """File feed: absorb every ``fleet_p*.json`` in ``obs_dir``.
        Returns how many snapshots are now held."""
        for snap in load_process_snapshots(obs_dir):
            self.push(snap)
        return len(self._snaps)

    @property
    def num_processes(self) -> int:
        return len(self._snaps)

    def merged(self) -> MetricsRegistry:
        snaps = [self._snaps[k] for k in sorted(self._snaps)]
        return merge_snapshots(snaps)

    def export(self, obs_dir: str) -> MetricsRegistry:
        """Write the merged registry as ``fleet_merged.prom`` (Prometheus
        text) and ``fleet_merged.json`` (plain snapshot) into ``obs_dir``;
        returns the merged registry."""
        reg = self.merged()
        os.makedirs(obs_dir, exist_ok=True)
        with open(os.path.join(obs_dir, "fleet_merged.prom"), "w") as f:
            f.write(_export.prometheus_text(reg))
        with open(os.path.join(obs_dir, "fleet_merged.json"), "w") as f:
            f.write(json.dumps(_export.registry_snapshot(reg), default=str))
        return reg
