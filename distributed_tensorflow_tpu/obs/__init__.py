"""Unified observability: metrics registry, span tracing, flight recorder.

The reference's only telemetry was ``time.time()`` deltas printed to stdout
and ``tf.summary`` events (``demo1/train.py:151-164``). The reproduction had
outgrown that into scattered islands — ``utils/summary.py`` TensorBoard
events, ``utils/profiler.py`` XPlanes, ``serve/metrics.py`` histograms,
``train/checkpoint.py``'s ``stall_seconds`` — with no single registry, no
scrape surface, and no crash-time record. This package is the one layer they
all report into:

* :mod:`registry <.registry>` — thread-safe process-wide Counter / Gauge /
  Histogram families (Prometheus-style pull metrics). ``serve/metrics.py``
  is built on it; the train loops publish their step-time decomposition
  (data-wait vs device compute vs checkpoint stall), rates, and
  ``skipped_nonfinite`` into it.
* :mod:`trace <.trace>` — Dapper-style context-manager spans with
  parent/child nesting, wall + monotonic clocks, and the process index.
  Closed spans feed the flight recorder.
* :mod:`recorder <.recorder>` — a fixed-size in-memory ring buffer of the
  last N spans/events, dumped to JSONL on preemption, rollback, or any
  unhandled exception, so every crash ships its timeline.
* :mod:`export <.export>` — Prometheus text exposition, JSONL snapshots,
  and a bridge into the repo's own ``SummaryWriter``; wired into
  ``serve/server.py`` as ``/metrics`` and into the tool CLIs via
  ``--obs_dir``.
* :mod:`aggregate <.aggregate>` — cross-process merge of per-process
  registries into one fleet view (counters sum, gauges get a ``process``
  label + min/max/sum rollups, histograms merge buckets exactly), fed by
  atomic ``fleet_p<i>.json`` snapshots in a shared ``--obs_dir``.
* :mod:`perf <.perf>` — live MFU / tokens-per-second gauges from the
  ``utils/flops.py`` math, device-memory watermarks, and the recompile
  sentinel that turns the serving engine's zero-recompile-after-warmup
  invariant into an alerting runtime counter.
* :mod:`slo <.slo>` — declarative SLO rules (selector, aggregation,
  threshold, sustain window) evaluated on a ticker; sustained breaches
  bump ``slo_breach_total``, hit the trace + flight-recorder planes, and
  invoke registered callbacks (the autoscaling/drain hook).

Everything here is stdlib-only on the hot paths (numpy appears only in the
``SummaryWriter`` bridge) and costs nothing when disabled: ``disable()``
swaps the process default for a :class:`~.registry.NullRegistry`, whose
instruments are shared no-op singletons — the bench.py overhead gate holds
the instrumented MNIST step within 1% of that no-op baseline.
"""

from distributed_tensorflow_tpu.obs.aggregate import (
    FleetAggregator,
    full_snapshot,
    merge_snapshots,
    write_process_snapshot,
)
from distributed_tensorflow_tpu.obs.perf import (
    PerfGauges,
    RecompileSentinel,
    update_memory_gauges,
)
from distributed_tensorflow_tpu.obs.recorder import (
    FlightRecorder,
    get_recorder,
    install_excepthook,
    set_dump_dir,
    set_recorder,
)
from distributed_tensorflow_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    get_registry,
    set_registry,
)
from distributed_tensorflow_tpu.obs.slo import (
    SloMonitor,
    SloRule,
    default_fleet_rules,
    default_serving_rules,
    default_training_rules,
    parse_slo_flag,
    parse_slo_spec,
)
from distributed_tensorflow_tpu.obs.trace import current_span, span, trace_event

__all__ = [
    "FleetAggregator",
    "full_snapshot",
    "merge_snapshots",
    "write_process_snapshot",
    "PerfGauges",
    "RecompileSentinel",
    "update_memory_gauges",
    "SloMonitor",
    "SloRule",
    "default_fleet_rules",
    "default_serving_rules",
    "default_training_rules",
    "parse_slo_flag",
    "parse_slo_spec",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NullRegistry",
    "FlightRecorder",
    "get_registry",
    "set_registry",
    "get_recorder",
    "set_recorder",
    "set_dump_dir",
    "install_excepthook",
    "span",
    "trace_event",
    "current_span",
    "disable",
    "enable",
]


def disable() -> None:
    """Swap the process default registry for shared no-op instruments.
    Every call site that resolved its instruments from ``get_registry()``
    AFTER this point records nothing (the bench.py overhead baseline)."""
    set_registry(NullRegistry())


def enable() -> "MetricsRegistry":
    """Install (and return) a fresh live default registry."""
    reg = MetricsRegistry()
    set_registry(reg)
    return reg
