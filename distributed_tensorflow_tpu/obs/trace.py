"""Lightweight span tracing (Dapper-style, in-process).

``with span("checkpoint_save", step=120):`` measures a region with BOTH
clocks — wall (``time.time``, for correlating against logs and other hosts)
and monotonic (``time.monotonic``, for durations that survive NTP steps) —
and records the closed span into the flight recorder ring buffer
(:mod:`~distributed_tensorflow_tpu.obs.recorder`), so the last N spans are
what a crash dump ships.

Nesting is tracked per thread: a span opened inside another span carries its
``parent_id``, so the dump reconstructs the call tree (emergency_shutdown →
checkpoint_save → …). Span ids are a process-local counter — unique within
the process, and the recorded ``process`` index disambiguates across a
multi-host job's per-process dumps.

This is deliberately NOT the XPlane profiler (``utils/profiler.py``): that
is a sampled, heavyweight device timeline you turn on for a window; spans
are an always-on, microsecond-cost breadcrumb trail of HOST-side phases.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any

from distributed_tensorflow_tpu.obs import recorder as _recorder

__all__ = ["Span", "span", "trace_event", "current_span"]

_ids = itertools.count(1)
_local = threading.local()


def _process_index() -> int:
    """jax.process_index() without importing jax at module import time (the
    obs package must stay importable — and cheap — in non-JAX tooling)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — uninitialized backend
        return 0


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current_span() -> "Span | None":
    """The innermost open span on THIS thread (None outside any span)."""
    stack = _stack()
    return stack[-1] if stack else None


class Span:
    """One traced region. Context-manager use only — ``__exit__`` closes the
    span and records it; an exception inside the region is noted on the span
    (``error`` field) and re-raised."""

    __slots__ = (
        "name", "attrs", "span_id", "parent_id", "process",
        "t_wall", "t_mono", "end_mono", "duration_s", "error",
    )

    def __init__(self, name: str, attrs: dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self.span_id = next(_ids)
        parent = current_span()
        self.parent_id = parent.span_id if parent is not None else 0
        self.process = _process_index()
        self.t_wall = 0.0
        self.t_mono = 0.0
        self.end_mono = 0.0
        self.duration_s = 0.0
        self.error = ""

    def __enter__(self) -> "Span":
        self.t_wall = time.time()
        self.t_mono = time.monotonic()
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end_mono = time.monotonic()
        self.duration_s = self.end_mono - self.t_mono
        if exc is not None:
            self.error = f"{type(exc).__name__}: {exc}"
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        _recorder.get_recorder().record_span(self)
        return None  # never swallow

    def to_event(self) -> dict:
        ev = {
            "kind": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "process": self.process,
            "t_wall": self.t_wall,
            "t_mono": self.t_mono,
            "end_mono": self.end_mono,
            "duration_s": round(self.duration_s, 6),
        }
        if self.attrs:
            ev["attrs"] = self.attrs
        if self.error:
            ev["error"] = self.error
        return ev


def span(name: str, **attrs: Any) -> Span:
    """Open a traced region: ``with span("eval", step=200): ...``"""
    return Span(name, attrs)


def trace_event(name: str, **attrs: Any) -> None:
    """Record an instantaneous event (no duration) into the flight recorder
    — preemption requests, vetoes, rollbacks."""
    parent = current_span()
    _recorder.get_recorder().record(
        kind="event",
        name=name,
        process=_process_index(),
        parent_id=parent.span_id if parent is not None else 0,
        **attrs,
    )
