"""Exporters for the metrics registry: Prometheus text, JSONL, SummaryWriter.

Three audiences:

* A scraper (``GET /metrics`` on the serve server) gets the standard
  Prometheus text exposition — ``# HELP`` / ``# TYPE`` headers, labeled
  samples, and cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``
  histogram series (:func:`prometheus_text`).
* Offline tooling gets append-only JSONL snapshots
  (:func:`write_jsonl_snapshot`) — one line per scrape, trivially greppable
  and diffable across runs.
* TensorBoard gets the existing ``utils/summary.py`` event files via
  :func:`publish_to_summary` — counters/gauges as scalars, histograms as
  reservoir histograms — so nothing about the established workflow breaks.

:func:`parse_prometheus_text` is the minimal inverse of the text format
(name, labels, value). It exists so tests can ROUND-TRIP the exposition
instead of string-matching it, and so loadgen-style tools can read a live
``/metrics`` endpoint without a prometheus client dependency.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import TYPE_CHECKING

from distributed_tensorflow_tpu.obs import registry as _registry

if TYPE_CHECKING:
    from distributed_tensorflow_tpu.utils.summary import SummaryWriter

__all__ = [
    "prometheus_text",
    "parse_prometheus_text",
    "registry_snapshot",
    "write_jsonl_snapshot",
    "publish_to_summary",
]


def _fmt(v: float) -> str:
    """Prometheus sample values: integers render bare, +Inf as ``+Inf``."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def prometheus_text(registry=None) -> str:
    """Render a registry in the Prometheus text exposition format
    (``text/plain; version=0.0.4``)."""
    registry = registry if registry is not None else _registry.get_registry()
    lines: list[str] = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for label_values, inst in fam.children():
            if fam.kind in ("counter", "gauge"):
                ls = _label_str(fam.label_names, label_values)
                lines.append(f"{fam.name}{ls} {_fmt(inst.value)}")
            else:  # histogram
                for le, cum in inst.buckets():
                    ls = _label_str(fam.label_names, label_values,
                                    extra=(("le", _fmt(le)),))
                    lines.append(f"{fam.name}_bucket{ls} {cum}")
                with inst._lock:
                    count, total = inst.count, inst.total
                ls_inf = _label_str(fam.label_names, label_values,
                                    extra=(("le", "+Inf"),))
                ls = _label_str(fam.label_names, label_values)
                lines.append(f"{fam.name}_bucket{ls_inf} {count}")
                lines.append(f"{fam.name}_sum{ls} {_fmt(total)}")
                lines.append(f"{fam.name}_count{ls} {count}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> list[dict]:
    """Minimal parser for the exposition format: returns
    ``[{"name", "labels", "value"}, ...]`` for every sample line. Comment
    (``#``) and blank lines are skipped. ``le`` shows up as an ordinary
    label on ``_bucket`` series."""
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            label_part, value_part = rest.rsplit("}", 1)
            labels = {}
            # Split on commas outside quotes.
            buf, depth, parts = [], False, []
            for ch in label_part:
                if ch == '"' and (not buf or buf[-1] != "\\"):
                    depth = not depth
                if ch == "," and not depth:
                    parts.append("".join(buf))
                    buf = []
                else:
                    buf.append(ch)
            if buf:
                parts.append("".join(buf))
            for part in parts:
                if not part:
                    continue
                k, v = part.split("=", 1)
                v = v.strip().strip('"')
                v = v.replace('\\"', '"').replace("\\n", "\n")
                v = v.replace("\\\\", "\\")
                labels[k.strip()] = v
            value_s = value_part.strip().split()[0]
        else:
            fields = line.split()
            name, value_s = fields[0], fields[1]
            labels = {}
        if value_s == "+Inf":
            value = math.inf
        elif value_s == "-Inf":
            value = -math.inf
        else:
            value = float(value_s)
        samples.append({"name": name.strip(), "labels": labels, "value": value})
    return samples


def registry_snapshot(registry=None) -> dict:
    """JSON-friendly snapshot of every family: counters/gauges as values,
    histograms as their ``summary()`` dicts (per label set)."""
    registry = registry if registry is not None else _registry.get_registry()
    out: dict = {"t_wall": time.time(), "metrics": {}}
    for fam in registry.collect():
        entries = []
        for label_values, inst in fam.children():
            labels = dict(zip(fam.label_names, label_values))
            if fam.kind == "histogram":
                entry = {"labels": labels, **inst.summary()}
            else:
                entry = {"labels": labels, "value": inst.value}
            entries.append(entry)
        out["metrics"][fam.name] = {"kind": fam.kind, "samples": entries}
    return out


def write_jsonl_snapshot(path: str, registry=None) -> dict:
    """Append one :func:`registry_snapshot` line to ``path`` (JSONL). Returns
    the snapshot. Creates parent directories."""
    snap = registry_snapshot(registry)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(snap, default=str) + "\n")
    return snap


def publish_to_summary(writer: "SummaryWriter", step: int, registry=None) -> None:
    """Bridge registry families into the repo's TensorBoard writer: counters
    and gauges become scalars (labels joined into the tag), histograms become
    reservoir histograms plus a p99 scalar."""
    registry = registry if registry is not None else _registry.get_registry()
    for fam in registry.collect():
        for label_values, inst in fam.children():
            tag = fam.name
            if label_values:
                tag += "/" + "/".join(label_values)
            if fam.kind == "histogram":
                vals = inst.values()
                if vals.size:
                    writer.add_histogram(f"obs/{tag}", vals, step)
                writer.add_scalar(f"obs/{tag}_p99", inst.percentile(99), step)
            else:
                writer.add_scalar(f"obs/{tag}", inst.value, step)
