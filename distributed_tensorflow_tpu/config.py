"""Dataclass configs + argparse front-end.

Replaces the reference's flag system (argparse + module-global ``FLAGS`` +
``tf.app.run``, SURVEY C18). Flag names and defaults match the reference for
CLI parity:

* cluster flags — ``demo2/train.py:196-223`` (``--ps_hosts``, ``--worker_hosts``,
  ``--job_name``, ``--task_index``)
* retrain flags — ``retrain1/retrain.py:480-632`` and
  ``retrain2/retrain2.py:512-682`` (``--training_steps`` default differs:
  10000 single vs 2000 distributed)

Cluster semantics diverge deliberately: there are no parameter servers on TPU.
``--ps_hosts``/``--job_name=ps`` are accepted for CLI compatibility, but the
runtime is synchronous SPMD data-parallelism over a device mesh
(``--worker_hosts`` maps to JAX distributed processes; see
``parallel/distributed.py``).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Type, TypeVar

T = TypeVar("T")


def add_dataclass_flags(parser: argparse.ArgumentParser, cls: Type[Any]) -> None:
    """Auto-register one ``--flag`` per dataclass field (bools as 0/1-style
    store_true matching the reference's ``action='store_true'`` flags)."""
    for f in dataclasses.fields(cls):
        name = "--" + f.name
        default = f.default if f.default is not dataclasses.MISSING else f.default_factory()  # type: ignore[misc]
        help_text = f.metadata.get("help", "")
        if f.type in ("bool", bool):
            parser.add_argument(name, action="store_true", default=default, help=help_text)
        else:
            ftype = {"int": int, "float": float, "str": str}.get(str(f.type), type(default))
            parser.add_argument(name, type=ftype, default=default, help=help_text)


def from_args(cls: Type[T], args: argparse.Namespace) -> T:
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in vars(args).items() if k in names})


def parse_flags(*classes: Type[Any], argv=None):
    """Parse known args into one instance per dataclass (mirrors the
    reference's ``parser.parse_known_args()`` tolerance of unknown flags,
    ``demo2/train.py:222``). Also the shared CLI bootstrap: enables the
    persistent XLA compilation cache (``utils/compile_cache.py``)."""
    from distributed_tensorflow_tpu.utils.compile_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    parser = argparse.ArgumentParser()
    for cls in classes:
        add_dataclass_flags(parser, cls)
    ns, _ = parser.parse_known_args(argv)
    out = tuple(from_args(cls, ns) for cls in classes)
    return out[0] if len(out) == 1 else out


# ---------------------------------------------------------------------------
# Workload configs.
# ---------------------------------------------------------------------------


@dataclass
class MnistTrainConfig:
    """demo1/demo2 MNIST training (defaults from ``demo1/train.py:149-165``:
    10k steps, batch 100, Adam 1e-4, dropout keep_prob 0.7, eval every 100)."""

    data_dir: str = field(default="MNIST_data", metadata={"help": "idx .gz directory"})
    log_dir: str = field(default="./logs", metadata={"help": "summaries + autosave ckpts"})
    model_dir: str = field(default="./model", metadata={"help": "final checkpoint dir"})
    obs_dir: str = field(
        default="",
        metadata={"help": "observability output dir (flight-recorder crash "
                          "dumps + metrics JSONL + per-process fleet "
                          "snapshots, merged by the chief); empty disables "
                          "dumps"},
    )
    slo: str = field(
        default="",
        metadata={
            "help": "SLO rules evaluated at eval boundaries: 'default' "
            "(step time, data-wait fraction), 'off'/empty, and/or "
            "comma-separated 'metric[:agg]>thr[@sustain][#name]' specs"
        },
    )
    training_steps: int = 10000
    batch_size: int = 100
    model: str = field(
        default="cnn",
        metadata={"help": "classifier family: cnn (reference convnet) | vit"},
    )
    remat: bool = field(
        default=False,
        metadata={"help": "rematerialise transformer blocks (vit only)"},
    )
    learning_rate: float = 1e-4
    optimizer: str = field(
        default="adam",
        metadata={"help": "adam (reference demo parity) | adamw | sgd | momentum"},
    )
    lr_schedule: str = field(
        default="constant",
        metadata={"help": "constant (parity) | cosine | warmup_cosine | linear"},
    )
    warmup_steps: int = field(
        default=0, metadata={"help": "warmup_cosine ramp length"}
    )
    grad_clip_norm: float = field(
        default=0.0, metadata={"help": "global-norm gradient clip; 0 = off"}
    )
    dropout_rate: float = field(
        default=0.3, metadata={"help": "1 - keep_prob(0.7) from demo1/train.py:155"}
    )
    eval_step_interval: int = 100
    save_model_secs: int = field(
        default=600, metadata={"help": "Supervisor autosave parity, demo2/train.py:172"}
    )
    max_to_keep: int = field(
        default=5,
        metadata={"help": "checkpoints retained by the autosave manager"},
    )
    ckpt_async: int = field(
        default=1,
        metadata={
            "help": "zero-stall autosave: the device->host snapshot fetch "
            "and the disk write run on a background thread (forced/final "
            "saves still block until durable); 0 restores the synchronous "
            "fetch"
        },
    )
    snapshot_chunk_mb: int = field(
        default=64,
        metadata={
            "help": "chunk size of the double-buffered device->host "
            "snapshot copy (chunk i+1's transfer overlaps chunk i's "
            "materialization)"
        },
    )
    guard_nonfinite: int = field(
        default=1,
        metadata={
            "help": "skip optimizer updates whose global grad norm is "
            "non-finite (params/opt state untouched, step count advances, "
            "skipped_nonfinite metric emitted); 0 disables"
        },
    )
    rollback_bad_windows: int = field(
        default=2,
        metadata={
            "help": "after this many CONSECUTIVE eval windows containing "
            "non-finite (skipped) steps, roll back to the last good "
            "checkpoint; 0 disables rollback"
        },
    )
    max_rollbacks: int = field(
        default=3,
        metadata={
            "help": "give up (raise) after this many rollbacks in one run — "
            "a run that keeps diverging needs a human, not a loop"
        },
    )
    preempt_save: int = field(
        default=1,
        metadata={
            "help": "install SIGTERM/SIGINT handlers that trigger a "
            "coordinated emergency checkpoint at the next step boundary and "
            "exit cleanly; 0 disables"
        },
    )
    seed: int = 0
    synthetic_data: bool = field(
        default=False, metadata={"help": "generate deterministic synthetic MNIST if idx files absent"}
    )
    t10k_split: int = field(
        default=0,
        metadata={
            "help": "REAL-data mode for checkouts missing the 60k train-images "
            "blob: train on 10000-k of the genuine t10k digits, hold out k for "
            "eval (fixed split, independent of --seed); bundled copies in "
            "demo1/MNIST_data are used when --data_dir is left at its default"
        },
    )
    download_data: bool = field(
        default=False,
        metadata={
            "help": "fetch missing MNIST idx files first (the reference's "
            "auto-download; needs network egress)"
        },
    )
    profile_dir: str = field(
        default="",
        metadata={"help": "if set, write a jax.profiler (TensorBoard XPlane) trace here"},
    )
    profile_start_step: int = field(
        default=10, metadata={"help": "first traced step (after compile warmup)"}
    )
    profile_num_steps: int = field(default=5, metadata={"help": "traced step count"})
    steps_per_call: int = field(
        default=1,
        metadata={
            "help": "fuse k optimizer steps into one XLA dispatch (lax.scan) — "
            "amortizes per-step host overhead; semantics identical to k "
            "single steps"
        },
    )
    device_data: bool = field(
        default=False,
        metadata={
            "help": "keep the training set resident in HBM and sample batches "
            "on device inside the fused program (uniform per-shard sampling "
            "instead of epoch shuffling; fastest input path)"
        },
    )
    accum_steps: int = field(
        default=1,
        metadata={
            "help": "gradient accumulation: one optimizer step from k "
            "microbatch gradient means (effective batch k*batch_size whose "
            "activations never coexist in HBM); exclusive with "
            "steps_per_call>1 and device_data"
        },
    )
    export_stablehlo: bool = field(
        default=False,
        metadata={
            "help": "also export a frozen StableHLO inference program next to "
            "the final model bundle (weights baked in, runs without model code)"
        },
    )


@dataclass
class ClusterConfig:
    """PS/worker cluster flags (``demo2/train.py:196-223``), reinterpreted for
    SPMD: ``worker_hosts[0]`` is the coordinator, ``task_index`` the process
    index; ``ps_hosts`` is accepted-and-ignored (no parameter servers on TPU)."""

    ps_hosts: str = field(
        default="192.168.1.104:2221",
        metadata={"help": "accepted for CLI parity; unused (no PS on TPU)"},
    )
    # The reference defaulted to the author's two LAN IPs
    # (demo2/train.py:201,207) — with that default a bare invocation would
    # block waiting for a second process to join the coordination service.
    # Default here is single-process (all local devices); pass an explicit
    # multi-host list to go multi-process.
    worker_hosts: str = "localhost:12355"
    job_name: str = field(default="worker", metadata={"help": "'ps' exits with a notice"})
    task_index: int = 0
    initialization_timeout: int = field(
        default=120,
        metadata={
            "help": "seconds to wait for every worker to join the "
            "coordination service before failing loudly (a preempted or "
            "mis-addressed worker must not hang the job forever); 0 keeps "
            "the JAX default (300)"
        },
    )

    @property
    def worker_list(self) -> list[str]:
        return [h for h in self.worker_hosts.split(",") if h]

    @property
    def num_processes(self) -> int:
        return len(self.worker_list)

    @property
    def coordinator_address(self) -> str:
        return self.worker_list[0]

    @property
    def is_chief(self) -> bool:
        return self.task_index == 0


@dataclass
class RetrainConfig:
    """Transfer-learning flags, names/defaults from ``retrain1/retrain.py:480-632``."""

    image_dir: str = "./data"
    output_graph: str = field(
        default="./retrained_graph.msgpack",
        metadata={"help": "inference bundle (params); reference wrote a frozen .pb"},
    )
    output_labels: str = "./retrained_labels.txt"
    summaries_dir: str = "./retrain_logs"
    obs_dir: str = field(
        default="",
        metadata={"help": "observability output dir (per-process fleet "
                          "snapshots, merged by the chief); empty disables"},
    )
    training_steps: int = 10000
    learning_rate: float = 0.01
    optimizer: str = field(
        default="sgd",
        metadata={"help": "sgd (reference retrain parity) | adam | adamw | momentum"},
    )
    lr_schedule: str = field(
        default="constant",
        metadata={"help": "constant (parity) | cosine | warmup_cosine | linear"},
    )
    warmup_steps: int = 0
    grad_clip_norm: float = field(
        default=0.0, metadata={"help": "global-norm gradient clip; 0 = off"}
    )
    testing_percentage: int = 10
    validation_percentage: int = 10
    eval_step_interval: int = 10
    train_batch_size: int = 100
    test_batch_size: int = -1
    validation_batch_size: int = 100
    print_misclassified_test_images: bool = False
    model_dir: str = field(
        default="./inception_model",
        metadata={"help": "Inception-v3 weights dir (npz/msgpack); reference fetched a .pb"},
    )
    bottleneck_dir: str = "./bottleneck"
    final_tensor_name: str = "final_result"
    flip_left_right: bool = False
    random_crop: int = 0
    random_scale: int = 0
    random_brightness: int = 0
    seed: int = 0
    export_stablehlo: bool = field(
        default=False,
        metadata={
            "help": "also export a frozen StableHLO program next to "
            "--output_graph (closest analog of the reference's frozen .pb)"
        },
    )
    model_download_url: str = field(
        default="",
        metadata={
            "help": "when set and --model_dir has no weights, fetch+extract "
            "this .tgz first (the reference always downloaded "
            "inception-2015-12-05.tgz, retrain1/retrain.py:40-62; default off "
            "because this environment has no egress)"
        },
    )
    train_dir: str = field(
        default="",
        metadata={
            "help": "head-training checkpoint dir (Supervisor logdir parity, "
            "retrain2/retrain2.py:423-429: timed autosave + auto-restore); "
            "empty disables checkpointing (retrain1 reference behavior)"
        },
    )
    save_model_secs: int = field(
        default=600,
        metadata={"help": "autosave interval when --train_dir is set"},
    )
    max_to_keep: int = field(
        default=5,
        metadata={"help": "checkpoints retained when --train_dir is set"},
    )
    ckpt_async: int = field(
        default=1,
        metadata={
            "help": "zero-stall autosave (background snapshot + write) when "
            "--train_dir is set; 0 restores the synchronous fetch"
        },
    )
    snapshot_chunk_mb: int = field(
        default=64,
        metadata={
            "help": "chunk size of the double-buffered device->host "
            "snapshot copy"
        },
    )
    rollback_bad_windows: int = field(
        default=2,
        metadata={
            "help": "consecutive eval windows with non-finite (skipped) "
            "steps before rolling back to the last checkpoint (needs "
            "--train_dir); 0 disables"
        },
    )


@dataclass
class DistributedRetrainConfig(RetrainConfig):
    """retrain2 variant: ``--training_steps`` default 2000
    (``retrain2/retrain2.py:551``)."""

    training_steps: int = 2000


@dataclass
class ServeConfig:
    """Continuous-batching inference server (``serve/``, ``tools/serve_lm.py``).

    Beyond-reference: the source demos never serve. Defaults target the
    small-LM CPU/TPU demo path; production knobs are the slot count (batch
    capacity — more slots amortize weight reads until the KV read bound),
    ``steps_per_sync`` (decode micro-steps fused per host round-trip —
    raise on TPU where per-dispatch latency dominates small models), and
    the admission pair ``max_queue_depth``/``request_timeout_s``."""

    host: str = field(default="127.0.0.1", metadata={"help": "bind address"})
    port: int = field(default=8000, metadata={"help": "bind port; 0 = ephemeral"})
    slots: int = field(
        default=4, metadata={"help": "concurrent request capacity (batch lanes)"}
    )
    serve_max_len: int = field(
        default=0,
        metadata={"help": "per-slot KV capacity; 0 = model max_seq_len"},
    )
    prefill_len: int = field(
        default=0,
        metadata={"help": "padded prompt capacity; 0 = serve_max_len // 2"},
    )
    steps_per_sync: int = field(
        default=1,
        metadata={
            "help": "decode micro-steps per jitted engine round (amortizes "
            "host dispatch; tokens are delivered in bursts of this size)"
        },
    )
    max_queue_depth: int = field(
        default=64,
        metadata={"help": "queued requests beyond which submits shed (429)"},
    )
    request_timeout_s: float = field(
        default=60.0,
        metadata={"help": "HTTP handler wait before a 503 timeout answer"},
    )
    serve_log_dir: str = field(
        default="",
        metadata={"help": "if set, publish serving metrics to TB events here"},
    )
    obs_dir: str = field(
        default="",
        metadata={"help": "observability output dir (flight-recorder crash "
                          "dumps + metrics JSONL); empty disables dumps"},
    )
    metrics_interval_s: float = field(
        default=10.0, metadata={"help": "TB publish period"}
    )
    slo: str = field(
        default="default",
        metadata={
            "help": "SLO rules: 'default' (p99 TTFT, queue depth, "
            "post-warmup recompiles), 'off', and/or comma-separated "
            "'metric[:agg]>thr[@sustain][#name]' specs (obs/slo.py)"
        },
    )
    slo_interval_s: float = field(
        default=1.0,
        metadata={"help": "SLO monitor evaluation tick period"},
    )
    drain_deadline_s: float = field(
        default=10.0,
        metadata={
            "help": "SIGTERM grace: seconds the server keeps finishing "
            "accepted work (healthz 503, no new submits) before hard stop"
        },
    )
    lane_weights: str = field(
        default="8,4,1",
        metadata={
            "help": "admissions per scheduling cycle for priority lanes "
            "0 (interactive), 1 (normal), 2 (batch) under contention"
        },
    )
    page_size: int = field(
        default=-1,
        metadata={
            "help": "KV page size in tokens: -1 = auto (16 when it divides "
            "serve_max_len, else one whole-row page), 0 = monolithic "
            "per-slot KV (legacy layout), >0 = explicit page size"
        },
    )
    kv_pages: int = field(
        default=0,
        metadata={
            "help": "physical KV pages in the paged pool; 0 = worst case "
            "(slots * pages_per_slot + trash). Sizing below worst case "
            "oversubscribes: admission then gates on pages-free"
        },
    )
    prefix_cache: bool = field(
        default=True,
        metadata={
            "help": "adopt shared-prefix KV pages copy-free (paged layout "
            "only); shared-system-prompt traffic prefills only the tail"
        },
    )
    spec_k: int = field(
        default=0,
        metadata={
            "help": "speculative drafts per verify round (greedy requests, "
            "paged layout); 0 disables (default — opt in where the "
            "drafter fits the traffic; the verify program is one more "
            "warmup compile). Output is token-identical to plain "
            "decoding — this only changes latency"
        },
    )
    spec_branches: int = field(
        default=1,
        metadata={
            "help": "draft-tree branches per speculative verify round "
            "(requires spec_k > 0): 1 = linear drafts (default), N > 1 = "
            "a shared draft tree per slot (branch 0 the linear drafter, "
            "extras pooled from every active slot's history) verified in "
            "one widened forward under a tree-attention mask. Greedy "
            "output stays token-identical; sampled lanes stay lossless "
            "(multi-candidate rejection sampling)"
        },
    )
    kv_dtype: str = field(
        default="",
        metadata={
            "help": "live KV-cache page format: '' = model default, "
            "'bf16' = compute-dtype rows (explicit native), 'int8' = "
            "quantize-on-write int8 rows + per-row f32 scales with "
            "dequant fused on attend (~0.27x KV bytes/token vs f32; "
            "works under SlotEngine and ShardedSlotEngine — scale "
            "planes shard on the kv-head axis like the rows)"
        },
    )
    prefill_chunk_tokens: int = field(
        default=0,
        metadata={
            "help": "chunked-prefill budget per engine iteration (paged "
            "layout): prompts whose tail exceeds this width prefill in "
            "chunks interleaved with decode steps, so prompts beyond "
            "prefill_len are admissible and long prefills never stall "
            "co-resident decodes. 0 = auto (prefill_len), -1 = off "
            "(prefill_len stays a hard prompt cap)"
        },
    )
    draft_model: str = field(
        default="",
        metadata={
            "help": "path to a tools/train_draft.py bundle: a small "
            "distilled draft LM replacing the n-gram drafter for "
            "spec_k rounds (greedy output stays token-identical — a "
            "better drafter only raises the accept rate). Empty = "
            "n-gram prompt-lookup drafting"
        },
    )
    draft_window: int = field(
        default=16,
        metadata={
            "help": "history suffix (tokens) the draft model conditions "
            "on per round; clamped to the draft bundle's max_seq_len "
            "minus spec_k"
        },
    )
    tp: int = field(
        default=1,
        metadata={
            "help": "tensor-parallel width of the serving mesh: 1 = one "
            "fully-replicated device (SlotEngine), N > 1 = one model "
            "partitioned over N devices behind the same slot API "
            "(ShardedSlotEngine; requires num_kv_heads % tp == 0 and "
            "d_model % tp == 0, validated before any jit)"
        },
    )
    weight_dtype: str = field(
        default="",
        metadata={
            "help": "weight-only quantization for serving: '' = the "
            "bundle's native weights, 'int8' = symmetric per-channel "
            "(scales factor out of the matmul exactly), 'int4' = "
            "group-wise along the input axis (needs quant_group_size; "
            "dequant in-register). Embeddings/norms/lm_head stay "
            "high-precision (models/quant.py)"
        },
    )
    quant_group_size: int = field(
        default=0,
        metadata={
            "help": "int4 scale-group size along the matmul input axis "
            "(even, dividing d_model and d_ff — e.g. 32/64/128); must be "
            "0 for '' / 'int8'"
        },
    )
    role: str = field(
        default="mixed",
        metadata={
            "help": "disaggregated-tier role: 'prefill' (runs prompt "
            "prefill + first token, then hands the slot's KV pages to a "
            "decode peer), 'decode' (imports handed-off slots via POST "
            "/handoff), 'mixed' (classic single-tier replica; default)"
        },
    )
    handoff_peers: str = field(
        default="",
        metadata={
            "help": "comma-separated decode-tier base URLs a prefill "
            "replica pushes handoffs to (also settable at runtime via "
            "POST /admin/handoff_peers)"
        },
    )
    handoff_wire: int = field(
        default=2,
        metadata={
            "help": "handoff wire format a prefill replica SENDS: 2 = "
            "chunked pipelined DTFH2 stream (default; encode overlaps "
            "send, per-chunk CRC, optional zlib), 1 = monolithic DTFH1 "
            "bundle. Receivers always accept both"
        },
    )
    handoff_chunk_pages: int = field(
        default=4,
        metadata={
            "help": "KV pages per DTFH2 chunk frame — the pipelining "
            "grain: smaller = better encode/send overlap + finer "
            "receiver scatters, larger = less framing overhead"
        },
    )
    handoff_compress: bool = field(
        default=True,
        metadata={
            "help": "zlib-compress DTFH2 chunk payloads when the "
            "measured ratio clears the skip-if-incompressible guard "
            "(stdlib zlib level 1; incompressible chunks ship raw)"
        },
    )

    @property
    def handoff_peer_list(self) -> tuple:
        return tuple(u.strip() for u in self.handoff_peers.split(",")
                     if u.strip())

    @property
    def lane_weight_tuple(self) -> tuple:
        return tuple(int(w) for w in self.lane_weights.split(","))

    @property
    def engine_page_size(self) -> int | None:
        """Resolve the ``page_size`` flag for SlotEngine: None = engine
        auto-pick, 0 = monolithic, else the explicit value."""
        return None if self.page_size < 0 else self.page_size

    def validate_mesh(self, model_cfg) -> None:
        """Fail fast — at config-build time, with an actionable message —
        on a ``tp`` the model's shapes cannot shard, instead of a shape
        error deep inside jit. No-op for ``tp <= 1``."""
        if self.tp > 1:
            validate_tp_mesh(model_cfg, self.tp)

    def validate_quant(self, model_cfg) -> None:
        """Fail fast on a weight-quantization config the model's shapes
        cannot satisfy (group-size divisibility, int4-requires-grouping,
        int4-under-tp group alignment) — the ``validate_mesh`` discipline
        for the ``weight_dtype``/``quant_group_size`` pair. No-op when
        quantization is off."""
        if self.weight_dtype or self.quant_group_size:
            from distributed_tensorflow_tpu.models.quant import (
                validate_weight_quant,
            )

            validate_weight_quant(
                self.weight_dtype or None, self.quant_group_size,
                int(model_cfg.d_model), int(model_cfg.d_ff),
                tp=max(1, int(self.tp)),
            )

    def validate_kv(self) -> None:
        """Fail fast on a KV-format / speculation combination the engine
        would reject anyway — at config-build time, with the flag names in
        the message."""
        if self.kv_dtype not in ("", "bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be '', 'bf16' or 'int8', got "
                f"{self.kv_dtype!r}"
            )
        if self.spec_branches < 1:
            raise ValueError(
                f"spec_branches must be >= 1, got {self.spec_branches}"
            )
        if self.spec_branches > 1 and not self.spec_k:
            raise ValueError(
                "spec_branches > 1 requires spec_k > 0 (tree speculation "
                "widens the verify block; there is nothing to widen "
                "without drafts)"
            )

    @property
    def engine_kv_cache_dtype(self):
        """Resolve ``kv_dtype`` to ``TransformerConfig.kv_cache_dtype``:
        ``''`` keeps the model bundle's own setting (no override),
        ``'bf16'`` forces native compute-dtype rows (``None``), ``'int8'``
        forces quantize-on-write int8 pages. Returns the sentinel string
        ``'keep'`` for no-override so callers can distinguish it from an
        explicit ``None``."""
        if not self.kv_dtype:
            return "keep"
        return "int8" if self.kv_dtype == "int8" else None


def validate_tp_mesh(model_cfg, tp: int) -> None:
    """Shared tp-divisibility check (ServeConfig AND ShardedSlotEngine call
    this). ``model_cfg`` needs ``kv_heads`` and ``d_model`` attributes."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    kv = int(model_cfg.kv_heads)
    if kv % tp:
        divisors = [d for d in range(1, kv + 1) if kv % d == 0]
        raise ValueError(
            f"tp={tp} does not divide num_kv_heads={kv}: GQA-under-TP "
            "shards whole query groups along the kv-head axis (KV pages "
            "included), so num_kv_heads % tp must be 0. Pick tp from "
            f"{divisors} or change the model's num_kv_heads."
        )
    dm = int(model_cfg.d_model)
    if dm % tp:
        raise ValueError(
            f"tp={tp} does not divide d_model={dm}: the column/row-"
            "parallel kernels split the model dim evenly across the "
            "'model' mesh axis. Pick a tp that divides d_model."
        )


@dataclass
class FleetConfig:
    """Router tier over N replicas (``serve/fleet/``,
    ``tools/serve_fleet.py``). Flag names carry a ``router_``/``fleet_``
    prefix so they compose with :class:`ServeConfig` in one parser (the
    launcher forwards the serve flags to every replica)."""

    router_host: str = field(default="127.0.0.1", metadata={"help": "router bind address"})
    router_port: int = field(
        default=8100, metadata={"help": "router bind port; 0 = ephemeral"}
    )
    num_replicas: int = field(
        default=2, metadata={"help": "local replicas the launcher spawns"}
    )
    probe_interval_s: float = field(
        default=0.25, metadata={"help": "health-check period per replica"}
    )
    up_after: int = field(
        default=2,
        metadata={"help": "consecutive healthy probes before down->up"},
    )
    down_after: int = field(
        default=2,
        metadata={"help": "consecutive failed probes before up->down"},
    )
    max_attempts: int = field(
        default=3,
        metadata={"help": "dispatch tries per request (1 + failovers)"},
    )
    fleet_slo: str = field(
        default="default",
        metadata={
            "help": "router SLO rules: 'default' (fleet_pressure, up-replica "
            "floor, routed p99 TTFT), 'off', and/or compact specs"
        },
    )
    fleet_slo_interval_s: float = field(
        default=1.0, metadata={"help": "router SLO evaluation tick period"}
    )
    # Disaggregated tiers: when either count is > 0 the launcher spawns
    # role-tagged replicas instead of num_replicas mixed ones and pushes
    # the decode tier's URLs to every prefill replica's handoff outbox.
    prefill_replicas: int = field(
        default=0,
        metadata={"help": "prefill-tier replicas (0 = no disaggregation; "
                  "with decode_replicas, replaces num_replicas)"},
    )
    decode_replicas: int = field(
        default=0,
        metadata={"help": "decode-tier replicas receiving KV-page "
                  "handoffs (0 = no disaggregation)"},
    )
    # Elastic supervision (tools/serve_fleet.py --supervise).
    supervise: bool = field(
        default=False,
        metadata={"help": "run the FleetSupervisor: replica processes "
                  "become supervised + autoscaled instead of a static "
                  "launch list (replacements re-announce on stdout)"},
    )
    min_replicas: int = field(
        default=1,
        metadata={"help": "autoscaler floor (supervised mode)"},
    )
    max_replicas: int = field(
        default=4,
        metadata={"help": "autoscaler ceiling (supervised mode)"},
    )
    scale_high_watermark: float = field(
        default=0.85,
        metadata={"help": "fleet_pressure above this (sustained) scales "
                  "up"},
    )
    scale_low_watermark: float = field(
        default=0.25,
        metadata={"help": "fleet_pressure below this (sustained) scales "
                  "down"},
    )
    scale_up_sustain_s: float = field(
        default=1.0,
        metadata={"help": "seconds pressure must hold above the high "
                  "watermark before a scale-up"},
    )
    scale_down_sustain_s: float = field(
        default=10.0,
        metadata={"help": "seconds pressure must hold below the low "
                  "watermark before a scale-down"},
    )
    scale_cooldown_s: float = field(
        default=5.0,
        metadata={"help": "seconds after any scaling decision during "
                  "which no further decision is taken (flap control)"},
    )
    supervisor_tick_s: float = field(
        default=0.5, metadata={"help": "policy loop evaluation period"}
    )
    balance_tiers: bool = field(
        default=False,
        metadata={"help": "supervised disaggregated fleets only: each "
                  "scaling decision picks WHICH tier to grow/shrink from "
                  "the prefill admission-load vs decode page-occupancy "
                  "split instead of always scaling the fixed role"},
    )
    drain_grace_s: float = field(
        default=15.0,
        metadata={"help": "scale-down drain window: SIGTERM -> graceful "
                  "drain -> SIGKILL after this many seconds"},
    )
    # Chaos defenses (PR 16): hedging, circuit breakers, read watchdog.
    hedge_after_s: float = field(
        default=-1.0,
        metadata={"help": "tail-latency hedge delay for buffered "
                  "dispatches: <0 = disabled, 0 = adaptive (p95 of the "
                  "router's recent latency window), >0 = fixed seconds"},
    )
    read_timeout_s: float = field(
        default=30.0,
        metadata={"help": "per-attempt upstream read watchdog: a replica "
                  "that accepts the connection but never answers is "
                  "treated as a dispatch failure (feeds its breaker) "
                  "instead of holding the request forever"},
    )
    breaker_window: int = field(
        default=8,
        metadata={"help": "dispatch outcomes per replica scored for the "
                  "circuit breaker (sliding window)"},
    )
    breaker_fail_threshold: float = field(
        default=0.5,
        metadata={"help": "failure fraction over the window that trips a "
                  "replica's breaker open"},
    )
    breaker_min_samples: int = field(
        default=4,
        metadata={"help": "minimum outcomes in the window before the "
                  "breaker may trip (single blips never open it)"},
    )
    breaker_open_s: float = field(
        default=2.0,
        metadata={"help": "seconds a tripped breaker stays open before "
                  "admitting one half-open trial dispatch"},
    )
    router_obs_dir: str = field(
        default="",
        metadata={"help": "router-side observability dir: breaker-open "
                  "flight-recorder dumps + the end-of-run "
                  "fleet_storm_summary.json land here (distinct from "
                  "--obs_dir, which is forwarded to every replica)"},
    )


@dataclass
class DeployConfig:
    """Checkpoint hot-swap + canary + variants (``serve/deploy/``).

    All off by default: with ``watch_dir`` empty no watcher starts and
    the serving stack is byte-identical to the pre-deploy build. Flags
    carry a ``deploy_``/``canary_`` prefix so they compose with
    :class:`ServeConfig` / :class:`FleetConfig` in one parser."""

    watch_dir: str = field(
        default="",
        metadata={"help": "checkpoint dir to poll for committed steps; "
                  "empty = hot-swap disabled"},
    )
    watch_interval_s: float = field(
        default=0.25, metadata={"help": "watcher poll period"}
    )
    deploy_params_key: str = field(
        default="auto",
        metadata={"help": "subtree of the checkpoint to serve: 'auto' "
                  "(tree['params'] when present), '' (whole tree), or a "
                  "'/'-separated path"},
    )
    deploy_variant: str = field(
        default="",
        metadata={"help": "variant new checkpoints deploy into; empty = "
                  "the live/default variant (in-place hot swap)"},
    )
    canary_percent: float = field(
        default=0.0,
        metadata={"help": "percent of client_id hash lanes (0-100) routed "
                  "to the canary variant once it exists"},
    )
    canary_variant: str = field(
        default="canary",
        metadata={"help": "name of the canary variant in the table"},
    )
    canary_rows: int = field(
        default=4,
        metadata={"help": "held-out canary eval batch rows"},
    )
    canary_len: int = field(
        default=16,
        metadata={"help": "held-out canary eval sequence length"},
    )
    canary_probes: int = field(
        default=2,
        metadata={"help": "probe prompts greedily continued pre-flip"},
    )
    max_loss_ratio: float = field(
        default=1.5,
        metadata={"help": "candidate/live canary eval-loss ratio above "
                  "which the swap rolls back"},
    )

    def validate(self) -> None:
        if not 0.0 <= self.canary_percent <= 100.0:
            raise ValueError(
                f"canary_percent must be in [0, 100], got "
                f"{self.canary_percent}"
            )
        if self.max_loss_ratio <= 0:
            raise ValueError(
                f"max_loss_ratio must be > 0, got {self.max_loss_ratio}"
            )
        if self.watch_interval_s <= 0:
            raise ValueError(
                f"watch_interval_s must be > 0, got {self.watch_interval_s}"
            )
        if self.canary_rows < 1 or self.canary_len < 2:
            raise ValueError(
                "canary batch needs >= 1 row and length >= 2 (next-token "
                f"loss), got rows={self.canary_rows} len={self.canary_len}"
            )

    @property
    def enabled(self) -> bool:
        return bool(self.watch_dir)
